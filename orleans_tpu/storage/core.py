"""Grain persistence: provider abstraction + bridge + dev providers.

Re-design of /root/reference/src/Orleans.Core/Providers/IGrainStorage.cs and
/root/reference/src/Orleans.Runtime/Storage/StateStorageBridge.cs:11,49,80,107,
with the dev/test providers of OrleansProviders/Storage/MemoryStorage.cs and
``MemoryStorageWithLatency`` (fault/latency injection for tests).

Etag protocol: every stored record carries an opaque etag; writes must present
the etag from the last read/write or fail with InconsistentStateError, which
deactivates the activation (InsideRuntimeClient.cs:390-402) — resume = rebuild
from storage on the next call.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import os
import uuid
from typing import TYPE_CHECKING, Any

from ..core.errors import InconsistentStateError
from ..core.ids import GrainId
from ..core.serialization import deserialize, serialize, serialize_portable

if TYPE_CHECKING:
    from ..runtime.activation import ActivationData

__all__ = [
    "GrainStorage", "MemoryStorage", "FileStorage", "StorageManager",
    "StateStorageBridge", "ErrorInjectionStorage", "LatencyStorage",
]


class GrainStorage:
    """Provider interface (``IGrainStorage``): etag-checked read/write/clear
    keyed by (grain type name, grain id)."""

    async def read(self, grain_type: str, grain_id: GrainId
                   ) -> tuple[Any, str | None]:
        """Returns (state, etag); (None, None) when absent."""
        raise NotImplementedError

    async def write(self, grain_type: str, grain_id: GrainId, state: Any,
                    etag: str | None) -> str:
        """CAS write; returns the new etag; raises InconsistentStateError on
        etag mismatch."""
        raise NotImplementedError

    async def clear(self, grain_type: str, grain_id: GrainId,
                    etag: str | None) -> None:
        raise NotImplementedError


def _key(grain_type: str, grain_id: GrainId) -> tuple:
    return (grain_type, grain_id.uniform_hash, str(grain_id.key), grain_id.key_ext)


class MemoryStorage(GrainStorage):
    """In-memory dev provider (MemoryStorage.cs). Serializes state through the
    wire codec so storage isolation matches a real remote store."""

    def __init__(self) -> None:
        self._data: dict[tuple, tuple[bytes, str]] = {}

    async def read(self, grain_type, grain_id):
        rec = self._data.get(_key(grain_type, grain_id))
        if rec is None:
            return None, None
        blob, etag = rec
        return deserialize(blob), etag

    _etag_seq = itertools.count(1)

    async def write(self, grain_type, grain_id, state, etag):
        k = _key(grain_type, grain_id)
        cur = self._data.get(k)
        cur_etag = cur[1] if cur else None
        if etag != cur_etag:
            raise InconsistentStateError(
                f"etag mismatch for {grain_id}", stored_etag=cur_etag,
                current_etag=etag)
        # etags only need to be unique per store: a counter is ~3x
        # cheaper than uuid4 on the write-behind hot path
        new_etag = f"e{next(self._etag_seq)}"
        self._data[k] = (serialize(state), new_etag)
        return new_etag

    async def clear(self, grain_type, grain_id, etag):
        k = _key(grain_type, grain_id)
        cur = self._data.get(k)
        if cur is None:
            return
        if etag != cur[1]:
            raise InconsistentStateError(
                f"etag mismatch for {grain_id}", stored_etag=cur[1],
                current_etag=etag)
        self._data.pop(k, None)


def _file_read_blob(path: str) -> "tuple[bytes | None, str | None]":
    """Sync half of FileStorage.read — runs in the loop's thread executor
    so file IO never stalls grain turns (the OTPU002 discipline)."""
    try:
        with open(path, "rb") as f:
            meta_len = int.from_bytes(f.read(4), "little")
            meta = json.loads(f.read(meta_len))
            blob = f.read()
        return blob, meta["etag"]
    except FileNotFoundError:
        return None, None


def _file_write_blob(path: str, meta: bytes, blob: bytes) -> None:
    """Sync half of FileStorage.write (executor-run): tmp + atomic
    replace, so a crash mid-write never leaves a torn record."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(len(meta).to_bytes(4, "little"))
        f.write(meta)
        f.write(blob)
    os.replace(tmp, path)


class FileStorage(GrainStorage):
    """Durable single-host provider: one JSON-indexed blob dir. Plays the
    role of the reference's cloud table providers for local deployments.
    File IO runs through ``loop.run_in_executor`` — a slow disk stalls
    only the writing activation, never the whole silo's event loop. A
    per-store mutation lock keeps the etag check-then-write atomic across
    the executor suspensions (the pure-sync body used to get that for
    free from loop atomicity; concurrent CAS writers must still lose)."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._mutate_lock = asyncio.Lock()

    def _path(self, grain_type: str, grain_id: GrainId) -> str:
        name = f"{grain_type}-{grain_id.uniform_hash:016x}"
        return os.path.join(self.root, name)

    async def read(self, grain_type, grain_id):
        p = self._path(grain_type, grain_id)
        blob, etag = await asyncio.get_running_loop().run_in_executor(
            None, _file_read_blob, p)
        if blob is None:
            return None, None
        return deserialize(blob), etag

    async def write(self, grain_type, grain_id, state, etag):
        async with self._mutate_lock:
            _, cur_etag = await self.read(grain_type, grain_id)
            if etag != cur_etag:
                raise InconsistentStateError(
                    f"etag mismatch for {grain_id}", stored_etag=cur_etag,
                    current_etag=etag)
            new_etag = uuid.uuid4().hex
            meta = json.dumps({"etag": new_etag}).encode()
            # serialize on the loop (touches live state; executor threads
            # must only see immutable bytes), write in the executor
            blob = serialize_portable(state)
            await asyncio.get_running_loop().run_in_executor(
                None, _file_write_blob, self._path(grain_type, grain_id),
                meta, blob)
            return new_etag

    async def clear(self, grain_type, grain_id, etag):
        async with self._mutate_lock:
            _, cur_etag = await self.read(grain_type, grain_id)
            if cur_etag is None:
                return
            if etag != cur_etag:
                raise InconsistentStateError(
                    f"etag mismatch for {grain_id}", stored_etag=cur_etag,
                    current_etag=etag)
            os.remove(self._path(grain_type, grain_id))


# ---------------------------------------------------------------------------
# Test/fault-injection providers (ErrorInjectionStorageProvider,
# MemoryStorageWithLatency — test/TesterInternal/)
# ---------------------------------------------------------------------------

class ErrorInjectionStorage(GrainStorage):
    """Wraps a provider; raises on demand (ErrorInjectionStorageProvider)."""

    def __init__(self, inner: GrainStorage):
        self.inner = inner
        self.fail_reads = False
        self.fail_writes = False

    async def read(self, grain_type, grain_id):
        if self.fail_reads:
            raise IOError("injected read failure")
        return await self.inner.read(grain_type, grain_id)

    async def write(self, grain_type, grain_id, state, etag):
        if self.fail_writes:
            raise IOError("injected write failure")
        return await self.inner.write(grain_type, grain_id, state, etag)

    async def clear(self, grain_type, grain_id, etag):
        return await self.inner.clear(grain_type, grain_id, etag)


class LatencyStorage(GrainStorage):
    """Adds fixed latency (MemoryStorageWithLatency)."""

    def __init__(self, inner: GrainStorage, latency: float):
        self.inner = inner
        self.latency = latency

    async def read(self, grain_type, grain_id):
        await asyncio.sleep(self.latency)
        return await self.inner.read(grain_type, grain_id)

    async def write(self, grain_type, grain_id, state, etag):
        await asyncio.sleep(self.latency)
        return await self.inner.write(grain_type, grain_id, state, etag)

    async def clear(self, grain_type, grain_id, etag):
        await asyncio.sleep(self.latency)
        return await self.inner.clear(grain_type, grain_id, etag)


# ---------------------------------------------------------------------------
# Bridge + manager
# ---------------------------------------------------------------------------

class StateStorageBridge:
    """Per-activation storage facade holding the current etag
    (StateStorageBridge.cs:11,49,80,107). ``manager`` (when attached)
    counts in-flight operations — the storage queue-depth signal the
    metrics sampler reads."""

    def __init__(self, provider: GrainStorage, grain_type: str,
                 grain_id: GrainId, manager: "StorageManager | None" = None):
        self.provider = provider
        self.grain_type = grain_type
        self.grain_id = grain_id
        self.etag: str | None = None
        self.manager = manager

    def _prof(self):
        """Loop-occupancy hook: provider awaits run in THIS coroutine's
        context, so an enter("storage") here labels every resumption step
        during the provider call as storage IO on the loop (exit restores
        the surrounding turn's category). None when profiling is off."""
        mgr = self.manager
        return mgr.loop_prof if mgr is not None else None

    async def read(self):
        mgr = self.manager
        if mgr is not None:
            mgr.inflight += 1
        lp = self._prof()
        tok = lp.enter("storage") if lp is not None else None
        try:
            state, self.etag = await self.provider.read(
                self.grain_type, self.grain_id)
        finally:
            if tok is not None:
                lp.exit(tok)
            if mgr is not None:
                mgr.inflight -= 1
        return state

    async def write(self, state) -> None:
        mgr = self.manager
        if mgr is not None:
            mgr.inflight += 1
        lp = self._prof()
        tok = lp.enter("storage") if lp is not None else None
        try:
            self.etag = await self.provider.write(
                self.grain_type, self.grain_id, state, self.etag)
        finally:
            if tok is not None:
                lp.exit(tok)
            if mgr is not None:
                mgr.inflight -= 1

    async def clear(self) -> None:
        mgr = self.manager
        if mgr is not None:
            mgr.inflight += 1
        lp = self._prof()
        tok = lp.enter("storage") if lp is not None else None
        try:
            await self.provider.clear(self.grain_type, self.grain_id,
                                      self.etag)
        finally:
            if tok is not None:
                lp.exit(tok)
            if mgr is not None:
                mgr.inflight -= 1
        self.etag = None


class StorageManager:
    """Named-provider registry (the DI provider registration analog).
    ``inflight`` is the number of storage operations currently awaiting
    their provider (reads + writes + clears across every bridge minted by
    this manager) — sampled as ``storage.inflight_ops``."""

    DEFAULT = "Default"

    def __init__(self) -> None:
        self.providers: dict[str, GrainStorage] = {}
        self.inflight = 0
        # host-loop occupancy profiler (set by the owning silo when
        # profiling_enabled): bridges label their provider awaits as
        # "storage" loop time through this ref
        self.loop_prof = None

    def add(self, name: str, provider: GrainStorage) -> None:
        self.providers[name] = provider

    def get(self, name: str | None) -> GrainStorage:
        name = name or self.DEFAULT
        if name not in self.providers:
            if name == self.DEFAULT:
                # dev default, like AddMemoryGrainStorageAsDefault
                self.providers[name] = MemoryStorage()
            else:
                raise KeyError(f"no storage provider named {name!r}")
        return self.providers[name]

    def bridge_for(self, activation: "ActivationData") -> StateStorageBridge:
        provider = self.get(
            getattr(activation.grain_class, "STORAGE_PROVIDER", None))
        return StateStorageBridge(
            provider, activation.grain_class.__name__, activation.grain_id,
            manager=self)
