"""Checkpoint/resume for the device tier: orbax table snapshots +
write-behind per-actor persistence.

The reference has no cluster-wide checkpoint — durable truth is per-grain
storage (Grain<TState> via StateStorageBridge.cs:11,49,80,107) plus the
membership table (SURVEY.md §5 "Checkpoint / resume"). The TPU build keeps
that contract and adds the device-tier analog the survey prescribes:
sharded activation-state arrays periodically flushed via orbax-style async
checkpointing, plus a write-behind bridge that maps individual VectorGrain
rows onto the ordinary ``GrainStorage`` providers (the "TpuGrainStorage
IStorageProvider" of the north-star design) so a single actor's state
survives restart even without a full table snapshot.

Two recovery paths:
* **whole-silo resume** — ``VectorCheckpointer.save(step)`` every N ticks
  (synchronous D2H copy + write — see __init__ on why not async); after
  restart ``restore()`` rebuilds every table + its host bookkeeping.
* **per-actor lazy resume** — ``VectorStorageBridge.flush(keys)`` write-
  behind after ticks; on re-activation ``load(keys)`` scatters stored rows
  back into the table (the virtual-actor guarantee: the next call finds
  the state, wherever the actor lands).
"""

from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING, Iterable

import jax
import numpy as np

from ..core.errors import InconsistentStateError
from ..core.ids import GrainId, GrainType
from .core import GrainStorage

if TYPE_CHECKING:
    from ..dispatch.engine import VectorRuntime

__all__ = ["VectorCheckpointer", "VectorStorageBridge"]


class _ConflictReleased(Exception):
    """Internal flush marker: this key's etag conflicted (another silo
    flushed it since we last did), so the local row was released —
    deactivate-and-rebuild, never overwrite. Not a flush failure."""

    def __init__(self, key: int):
        super().__init__(key)
        self.key = key


def _table_meta(tbl) -> dict:
    return {
        "capacity": tbl.capacity,
        "dense_n": tbl.dense_n,
        "dense_per_shard": tbl.dense_per_shard,
        "dense_active": [int(i) for i in np.flatnonzero(tbl.dense_active)],
        "key_to_slot": {str(k): list(v) for k, v in tbl.key_to_slot.items()},
        "route_hash": {str(k): int(v) for k, v in tbl.route_hash.items()},
        "free": [list(f) for f in tbl.free],
    }


def _apply_meta(tbl, meta: dict) -> None:
    # capacity is taken from the checkpoint verbatim (the state arrays are
    # replaced wholesale right after; grow() would only churn buffers)
    tbl.capacity = meta["capacity"]
    tbl.dense_n = meta["dense_n"]
    tbl.dense_per_shard = meta["dense_per_shard"]
    tbl.dense_active = np.zeros(tbl.dense_n, dtype=bool)
    if meta["dense_active"]:
        tbl.dense_active[np.asarray(meta["dense_active"], int)] = True
    tbl.key_to_slot = {int(k): tuple(v)
                       for k, v in meta["key_to_slot"].items()}
    tbl.route_hash = {int(k): int(v)
                      for k, v in meta.get("route_hash", {}).items()}
    tbl.free = [list(f) for f in meta["free"]]


class VectorCheckpointer:
    """Orbax-backed snapshot of every ShardedActorTable in a VectorRuntime
    (state arrays + host bookkeeping), with retention and async writes."""

    def __init__(self, runtime: "VectorRuntime", directory: str,
                 max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.runtime = runtime
        # synchronous writes: the D2H copy (donation-safety, _state_tree)
        # is the dominant sync cost anyway, and orbax's async writer
        # shares process-global executors that race across manager
        # restarts (the in-process resume scenario TestCluster exercises)
        self.manager = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=False))

    def _state_tree(self) -> dict:
        # host copies, not device arrays: tick kernels DONATE the state
        # buffers (in-place updates), so a device array handed to the
        # writer can be deleted mid-save by the very next tick. The D2H
        # copy is the part that must happen before another tick runs.
        return {cls.__name__:
                {f: np.asarray(a) for f, a in tbl.state.items()}
                for cls, tbl in self.runtime.tables.items()}

    def capture(self) -> tuple[dict, dict]:
        """Donation-safe snapshot (synchronous D2H copy + bookkeeping).
        Taken under the engine's tick fence: with the off-loop tick
        worker, "runs on the loop" is no longer enough — a worker-side
        batch may have the state donated mid-dispatch, so the copy
        serializes against it. The returned tree is plain numpy — write
        it from any thread."""
        with self.runtime.tick_fence():
            state = self._state_tree()
            meta = {cls.__name__: _table_meta(tbl)
                    for cls, tbl in self.runtime.tables.items()}
        return state, meta

    def write(self, step: int, captured: tuple[dict, dict]) -> None:
        """Persist a captured snapshot (thread-safe; hosting runs this in
        a worker thread so the silo event loop keeps serving)."""
        ocp = self._ocp
        state, meta = captured
        self.manager.wait_until_finished()
        self.manager.save(step, args=ocp.args.Composite(
            state=ocp.args.StandardSave(state),
            meta=ocp.args.JsonSave(meta)))

    def save(self, step: int) -> None:
        """capture() + write() in one synchronous call."""
        self.write(step, self.capture())

    def wait(self) -> None:
        self.manager.wait_until_finished()

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def restore(self, step: int | None = None) -> int:
        """Rebuild every registered table from the checkpoint. The runtime
        must have the same grain classes registered (the schema IS the
        codegen contract; mismatch raises)."""
        ocp = self._ocp
        step = self.manager.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint to restore")
        by_name = {cls.__name__: tbl
                   for cls, tbl in self.runtime.tables.items()}
        # phase 1: bookkeeping only — validates registration before orbax
        # compares state trees
        meta = self.manager.restore(step, args=ocp.args.Composite(
            meta=ocp.args.JsonRestore()))["meta"]
        missing = set(meta) - set(by_name)
        if missing:
            raise KeyError(
                f"checkpoint has tables {sorted(missing)} not registered "
                f"on this runtime — register the grain classes first")
        # template shapes come from the checkpoint's own capacity, so a
        # runtime built with a different capacity_per_shard still restores
        template = {}
        for name in meta:
            tbl = by_name[name]
            cap = meta[name]["capacity"]
            template[name] = {
                f: jax.ShapeDtypeStruct(
                    (tbl.n_shards, cap + 1, *shape), dtype)
                for f, (dtype, shape) in tbl.grain_class.STATE.items()}
        state = self.manager.restore(step, args=ocp.args.Composite(
            state=ocp.args.StandardRestore(template)))["state"]
        for name in meta:
            tbl = by_name[name]
            _apply_meta(tbl, meta[name])
            tbl.restore({k: np.asarray(v) for k, v in state[name].items()})
        return step

    def close(self) -> None:
        self.manager.close()


class VectorStorageBridge:
    """Write-behind per-actor persistence for one VectorGrain class: rows
    flushed to / loaded from an ordinary ``GrainStorage`` provider, with
    the same etag discipline host grains get from StateStorageBridge."""

    def __init__(self, runtime: "VectorRuntime", grain_class: type,
                 storage: GrainStorage):
        self.runtime = runtime
        self.grain_class = grain_class
        self.storage = storage
        self.grain_type = grain_class.__name__
        self._etags: dict[int, str | None] = {}
        self.storage_conflicts = 0

    def _grain_id(self, key: int) -> GrainId:
        return GrainId.for_grain(GrainType.of(self.grain_type), int(key))

    def _locate(self, keys, drop_missing: bool = False
                ) -> tuple[list[int], np.ndarray, np.ndarray]:
        """Resolve keys to (surviving_keys, shards, slots). Keys with no
        activation slot raise KeyError, or are dropped with a log when
        ``drop_missing`` (a released slot has no row left to persist)."""
        tbl = self.runtime.table(self.grain_class)
        kept, shards, slots = [], [], []
        for k in keys:
            k = int(k)
            if 0 <= k < tbl.dense_n:
                shard, slot = k // tbl.dense_per_shard, k % tbl.dense_per_shard
            elif (loc := tbl.lookup(k)) is not None:
                shard, slot = loc[0], loc[1]
            elif drop_missing:
                logging.getLogger("orleans.vector").warning(
                    "write-behind: key %d has no activation slot; dropping",
                    k)
                continue
            else:
                raise KeyError(f"key {k} has no activation slot")
            kept.append(k)
            shards.append(shard)
            slots.append(slot)
        return kept, np.asarray(shards, np.int32), np.asarray(slots, np.int32)

    async def flush(self, keys: Iterable[int], strict: bool = False) -> int:
        """Write-behind: persist the current device rows for ``keys``.
        One batched device→host gather, then per-actor etag'd writes.

        Per-key failure isolation: keys whose activation slot is gone
        (released) are dropped with a log — there is no row left to
        persist — and keys whose storage write fails are re-marked dirty
        individually, so one bad key cannot wedge write-behind for the
        whole class. Failures re-raise (after re-marking) when ``strict``
        is set OR when the runtime has no dirty tracking to hold the
        retry — a standalone bridge must never report silent success."""
        keys = [int(k) for k in keys]
        if not keys:
            return 0
        tbl = self.runtime.table(self.grain_class)
        # under the tick fence: the gather materializes state rows, which
        # must not race an off-loop tick that has the state donated
        with self.runtime.tick_fence():
            kept, shards, slots = self._locate(keys, drop_missing=True)
            if not kept:
                return 0
            host = {f: np.asarray(a[shards, slots])
                    for f, a in tbl.state.items()}

        async def write_one(i: int, key: int) -> None:
            state = {f: host[f][i] for f in host}
            etag = self._etags.get(key)
            if etag is None:
                # adopt the stored etag (a fresh bridge after a checkpoint
                # restore has no etag memory but IS the legitimate writer —
                # the device row is the truth being flushed)
                _, etag = await self.storage.read(
                    self.grain_type, self._grain_id(key))
            try:
                etag = await self.storage.write(
                    self.grain_type, self._grain_id(key), state, etag)
            except InconsistentStateError:
                # another silo flushed this key since our last write: an
                # ownership move happened (partition-era vote, failover,
                # re-range). Reference semantics
                # (InsideRuntimeClient.cs:390-402): the conflicted
                # activation DEACTIVATES and rebuilds from storage on
                # next touch — never overwrite. Overwriting would let a
                # stale ex-owner silently REVERT durable state the live
                # owner wrote (fatal once the key goes quiet: no later
                # flush corrects it); releasing loses at most this
                # silo's not-yet-durable tail, which is the documented
                # write-behind loss window. The stale etag must also be
                # dropped or it would wedge this key's flushes forever
                self.storage_conflicts += 1
                self._etags.pop(key, None)
                if 0 <= key < tbl.dense_n:
                    tbl.dense_active[key] = False
                else:
                    tbl.release(key)
                logging.getLogger("orleans.vector").info(
                    "write-behind: etag conflict on key %d — row "
                    "released for rebuild from storage", key)
                raise _ConflictReleased(key) from None
            self._etags[key] = etag

        results = await asyncio.gather(
            *(write_one(i, k) for i, k in enumerate(kept)),
            return_exceptions=True)
        conflicts = [r.key for r in results
                     if isinstance(r, _ConflictReleased)]
        failed = [k for k, r in zip(kept, results)
                  if isinstance(r, BaseException)
                  and not isinstance(r, _ConflictReleased)]
        if failed:
            self.runtime._mark_dirty(self.grain_class, failed)
            first = next(r for r in results
                         if isinstance(r, BaseException)
                         and not isinstance(r, _ConflictReleased))
            logging.getLogger("orleans.vector").warning(
                "write-behind: %d/%d key writes failed (re-marked): %r",
                len(failed), len(kept), first)
            if strict or not self.runtime.track_dirty:
                # no retry mechanism will see the re-mark (or the caller
                # demanded completeness — the final stop() drain): surface
                # the failure instead of reporting partial success
                raise first
        return len(kept) - len(failed) - len(conflicts)

    async def load(self, keys: Iterable[int]) -> list[int]:
        """Resume: read stored rows and scatter them into the table.
        Returns the keys that had persisted state (missing keys keep
        their fresh-init state — the lazy-recreate contract)."""
        keys = [int(k) for k in keys]
        if not keys:
            return []
        tbl = self.runtime.table(self.grain_class)

        async def read_one(key: int):
            state, etag = await self.storage.read(
                self.grain_type, self._grain_id(key))
            return key, state, etag

        rows = await asyncio.gather(*(read_one(k) for k in keys))
        found = [(k, s, e) for k, s, e in rows if s is not None]
        if not found:
            return []
        for k, _, e in found:
            self._etags[k] = e
        fkeys = [k for k, _, _ in found]
        # claim slots for hashed keys that have no activation yet, and
        # record their routing hash (ownership sweeps need it for rows
        # that never entered through a routed call)
        for k in fkeys:
            if not (0 <= k < tbl.dense_n):
                if tbl.lookup(k) is None:
                    tbl.lookup_or_allocate(k)
                tbl.note_route(k, self._grain_id(k).uniform_hash)
        if tbl.dense_active.size:
            dense = [k for k in fkeys if 0 <= k < tbl.dense_n]
            if dense:
                tbl.dense_active[np.asarray(dense, int)] = True
        _, shards, slots = self._locate(fkeys)
        # under the tick fence: the per-field scatter reads and replaces
        # state arrays, which must not interleave with an off-loop tick
        # (the tick would commit a tree that predates — and erases — the
        # rehydrated rows)
        with self.runtime.tick_fence():
            for f, arr in tbl.state.items():
                vals = np.stack([np.asarray(s[f]) for _, s, _ in found])
                tbl.state[f] = tbl._put(arr.at[shards, slots].set(
                    jax.numpy.asarray(vals)))
        return fkeys
