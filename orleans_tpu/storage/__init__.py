"""Grain persistence providers (reference L11 persistence) + device-tier
checkpoint/resume (orbax table snapshots, write-behind row persistence)."""

from .checkpoint import VectorCheckpointer, VectorStorageBridge  # noqa: F401
from .core import (  # noqa: F401
    ErrorInjectionStorage,
    FileStorage,
    GrainStorage,
    LatencyStorage,
    MemoryStorage,
    StateStorageBridge,
    StorageManager,
)
