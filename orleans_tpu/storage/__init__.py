"""Grain persistence providers (reference L11 persistence)."""

from .core import (  # noqa: F401
    ErrorInjectionStorage,
    FileStorage,
    GrainStorage,
    LatencyStorage,
    MemoryStorage,
    StateStorageBridge,
    StorageManager,
)
