"""Multi-cluster gossip: configuration + gateway exchange between clusters.

Re-design of /root/reference/src/Orleans.Runtime/MultiClusterNetwork/
MultiClusterOracle.cs:12 + MultiClusterGossipChannelFactory.cs: each cluster
periodically merges its local view (its own gateways, stamped) with one or
more gossip channels (Azure-table-backed in the reference; an in-memory
shared object here) — last-writer-wins per cluster key.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING

from ..core.ids import SiloAddress

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.multicluster")

__all__ = ["MultiClusterData", "InMemoryGossipChannel", "MultiClusterOracle"]


@dataclass
class MultiClusterData:
    """Gossiped payload (MultiClusterData): per-cluster gateway lists +
    stamps; merge = per-key newest stamp wins."""

    clusters: dict[str, dict] = field(default_factory=dict)
    # clusters[cluster_id] = {"gateways": [SiloAddress], "stamp": float}

    def merge(self, other: "MultiClusterData") -> bool:
        changed = False
        for cid, entry in other.clusters.items():
            mine = self.clusters.get(cid)
            if mine is None or entry["stamp"] > mine["stamp"]:
                self.clusters[cid] = dict(entry)
                changed = True
        return changed

    def copy(self) -> "MultiClusterData":
        return MultiClusterData({k: dict(v) for k, v in self.clusters.items()})


class GossipChannel:
    """Shared gossip substrate (IGossipChannel)."""

    async def publish(self, data: MultiClusterData) -> None:
        raise NotImplementedError

    async def read(self) -> MultiClusterData:
        raise NotImplementedError


class InMemoryGossipChannel(GossipChannel):
    """Dev/test channel: one shared object across clusters (the Azure-table
    stand-in)."""

    def __init__(self) -> None:
        self._data = MultiClusterData()

    async def publish(self, data: MultiClusterData) -> None:
        self._data.merge(data)

    async def read(self) -> MultiClusterData:
        return self._data.copy()


class MultiClusterOracle:
    """Per-silo gossip oracle; silos of one cluster share a cluster_id."""

    def __init__(self, silo: "Silo", cluster_id: str,
                 channels: list[GossipChannel],
                 gossip_period: float = 1.0):
        self.silo = silo
        self.cluster_id = cluster_id
        self.channels = channels
        self.gossip_period = gossip_period
        self.data = MultiClusterData()
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            try:
                await self.gossip_once()
            except Exception:  # noqa: BLE001
                log.exception("gossip round failed")
            await asyncio.sleep(self.gossip_period)

    async def gossip_once(self) -> None:
        """One round: stamp our view, merge every channel, publish back."""
        self.data.clusters[self.cluster_id] = {
            "gateways": list(self.silo.locator.alive_list),
            "stamp": time.time(),
        }
        for ch in self.channels:
            remote = await ch.read()
            self.data.merge(remote)
            await ch.publish(self.data)

    # -- queries ---------------------------------------------------------
    def known_clusters(self) -> list[str]:
        return sorted(self.data.clusters)

    def gateways_of(self, cluster_id: str) -> list[SiloAddress]:
        entry = self.data.clusters.get(cluster_id)
        return list(entry["gateways"]) if entry else []


def add_multicluster(builder, cluster_id: str, channels: list,
                     gossip_period: float = 1.0):
    """Install a gossip oracle on a SiloBuilder (silo.multicluster)."""

    def install(silo) -> None:
        oracle = MultiClusterOracle(silo, cluster_id, channels, gossip_period)
        silo.multicluster = oracle
        from ..runtime.silo import ServiceLifecycleStage
        silo.subscribe_lifecycle(
            ServiceLifecycleStage.RUNTIME_GRAIN_SERVICES,
            oracle.start, oracle.stop)

    return builder.configure(install)
