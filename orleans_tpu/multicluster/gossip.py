"""Multi-cluster gossip: configuration + gateway exchange between clusters.

Re-design of /root/reference/src/Orleans.Runtime/MultiClusterNetwork/
MultiClusterOracle.cs:12 + MultiClusterGossipChannelFactory.cs: each cluster
periodically merges its local view (its own gateways, stamped) with one or
more gossip channels — last-writer-wins per cluster key. Channels are
pluggable (the factory's job): in-memory for tests, JSON-file and sqlite
for real multi-process deployments (the Azure-table channel stand-ins,
mirroring the membership-table backend split).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import sqlite3
import threading
import time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING

from ..core.ids import SiloAddress

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.multicluster")

__all__ = ["MultiClusterData", "InMemoryGossipChannel", "FileGossipChannel",
           "SqliteGossipChannel", "MultiClusterOracle"]


@dataclass
class MultiClusterData:
    """Gossiped payload (MultiClusterData): per-cluster gateway lists +
    stamps, plus the admin-injected configuration; merge = per-key (and
    for the config) newest stamp wins."""

    clusters: dict[str, dict] = field(default_factory=dict)
    # clusters[cluster_id] = {"gateways": [SiloAddress], "stamp": float}
    # admin-injected multi-cluster configuration
    # (MultiClusterConfiguration: timestamped cluster list + comment);
    # None until an operator injects one — gossip membership then governs
    config: dict | None = None
    # config = {"clusters": [str], "stamp": float, "comment": str}

    def merge(self, other: "MultiClusterData") -> bool:
        changed = False
        for cid, entry in other.clusters.items():
            mine = self.clusters.get(cid)
            if mine is None or entry["stamp"] > mine["stamp"]:
                self.clusters[cid] = dict(entry)
                changed = True
        if other.config is not None and (
                self.config is None
                or other.config["stamp"] > self.config["stamp"]):
            self.config = dict(other.config)
            changed = True
        return changed

    def copy(self) -> "MultiClusterData":
        return MultiClusterData(
            {k: dict(v) for k, v in self.clusters.items()},
            dict(self.config) if self.config else None)


class GossipChannel:
    """Shared gossip substrate (IGossipChannel)."""

    async def publish(self, data: MultiClusterData) -> None:
        raise NotImplementedError

    async def read(self) -> MultiClusterData:
        raise NotImplementedError


class InMemoryGossipChannel(GossipChannel):
    """Dev/test channel: one shared object across clusters (the Azure-table
    stand-in)."""

    def __init__(self) -> None:
        self._data = MultiClusterData()

    async def publish(self, data: MultiClusterData) -> None:
        self._data.merge(data)

    async def read(self) -> MultiClusterData:
        return self._data.copy()


_CONFIG_KEY = "__config__"  # reserved: not a valid cluster id


def _data_to_json(data: MultiClusterData) -> dict:
    out = {cid: {"stamp": e["stamp"],
                 "gateways": [[g.host, g.port, g.generation, g.mesh_index]
                              for g in e["gateways"]]}
           for cid, e in data.clusters.items()}
    if data.config is not None:
        out[_CONFIG_KEY] = dict(data.config)
    return out


def _data_from_json(raw: dict) -> MultiClusterData:
    config = raw.get(_CONFIG_KEY)
    return MultiClusterData({
        cid: {"stamp": e["stamp"],
              "gateways": [SiloAddress(h, p, g, m)
                           for h, p, g, m in e["gateways"]]}
        for cid, e in raw.items() if cid != _CONFIG_KEY},
        dict(config) if config else None)


class FileGossipChannel(GossipChannel):
    """Cross-process channel: one JSON file shared by all clusters.
    Publish is read-merge-write with an atomic replace; the merge is
    commutative and idempotent (per-cluster last-writer-wins), so a lost
    race between two processes heals on the next gossip round."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = asyncio.Lock()

    def _load(self) -> MultiClusterData:
        try:
            with open(self.path, encoding="utf-8") as f:
                return _data_from_json(json.load(f))
        except (FileNotFoundError, json.JSONDecodeError):
            return MultiClusterData()

    async def publish(self, data: MultiClusterData) -> None:
        def write() -> None:
            merged = self._load()
            merged.merge(data)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(_data_to_json(merged), f)
            os.replace(tmp, self.path)

        async with self._lock:  # file I/O off the loop (like the sqlite
            # sibling): a slow/network filesystem must not stall turns
            await asyncio.get_running_loop().run_in_executor(None, write)

    async def read(self) -> MultiClusterData:
        async with self._lock:
            return await asyncio.get_running_loop().run_in_executor(
                None, self._load)


class SqliteGossipChannel(GossipChannel):
    """Cross-process channel over sqlite (real database locking): one row
    per cluster id, last-writer-wins by stamp."""

    def __init__(self, path: str) -> None:
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._dblock = threading.Lock()
        with self._dblock:
            self._db.execute("PRAGMA journal_mode=WAL")
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS gossip ("
                " cluster TEXT PRIMARY KEY, stamp REAL, gateways TEXT)")
            self._db.commit()

    def close(self) -> None:
        with self._dblock:
            self._db.close()

    async def publish(self, data: MultiClusterData) -> None:
        def write() -> None:
            with self._dblock:
                for cid, e in data.clusters.items():
                    row = self._db.execute(
                        "SELECT stamp FROM gossip WHERE cluster=?",
                        (cid,)).fetchone()
                    if row is None or e["stamp"] > row[0]:
                        self._db.execute(
                            "INSERT OR REPLACE INTO gossip VALUES (?,?,?)",
                            (cid, e["stamp"], json.dumps(
                                [[g.host, g.port, g.generation, g.mesh_index]
                                 for g in e["gateways"]])))
                if data.config is not None:
                    # the admin configuration rides the same table under a
                    # reserved key; the gateways column carries its JSON
                    row = self._db.execute(
                        "SELECT stamp FROM gossip WHERE cluster=?",
                        (_CONFIG_KEY,)).fetchone()
                    if row is None or data.config["stamp"] > row[0]:
                        self._db.execute(
                            "INSERT OR REPLACE INTO gossip VALUES (?,?,?)",
                            (_CONFIG_KEY, data.config["stamp"],
                             json.dumps(data.config)))
                self._db.commit()

        await asyncio.get_running_loop().run_in_executor(None, write)

    async def read(self) -> MultiClusterData:
        def load() -> MultiClusterData:
            with self._dblock:
                rows = self._db.execute(
                    "SELECT cluster, stamp, gateways FROM gossip").fetchall()
            config = None
            clusters = {}
            for cid, stamp, gws in rows:
                if cid == _CONFIG_KEY:
                    config = json.loads(gws)
                else:
                    clusters[cid] = {
                        "stamp": stamp,
                        "gateways": [SiloAddress(h, p, g, m)
                                     for h, p, g, m in json.loads(gws)]}
            return MultiClusterData(clusters, config)

        return await asyncio.get_running_loop().run_in_executor(None, load)


class MultiClusterOracle:
    """Per-silo gossip oracle; silos of one cluster share a cluster_id."""

    def __init__(self, silo: "Silo", cluster_id: str,
                 channels: list[GossipChannel],
                 gossip_period: float = 1.0):
        self.silo = silo
        self.cluster_id = cluster_id
        self.channels = channels
        self.gossip_period = gossip_period
        self.data = MultiClusterData()
        self._task: asyncio.Task | None = None
        # fired (sync, with the new config dict) whenever a NEWER admin
        # configuration lands — whether injected locally or learned
        # through gossip; the GSI runtime hooks this for removed-cluster
        # entry demotion
        self.config_listeners: list = []

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            try:
                await self.gossip_once()
            except Exception:  # noqa: BLE001
                log.exception("gossip round failed")
            await asyncio.sleep(self.gossip_period)

    async def gossip_once(self) -> None:
        """One round: stamp our view, merge every channel, publish back.
        A newer admin configuration learned from any channel fires the
        config listeners."""
        self.data.clusters[self.cluster_id] = {
            "gateways": list(self.silo.locator.alive_list),
            "stamp": time.time(),
        }
        before = self.config_stamp()
        for ch in self.channels:
            remote = await ch.read()
            self.data.merge(remote)
            await ch.publish(self.data)
        if self.config_stamp() != before:
            self._fire_config_listeners()

    def _fire_config_listeners(self) -> None:
        for fn in list(self.config_listeners):
            try:
                fn(self.data.config)
            except Exception:  # noqa: BLE001 — listeners are best-effort
                log.exception("multicluster config listener failed")

    # -- admin configuration (ManagementGrain.cs:387-427 backing) ---------
    def config_stamp(self) -> float | None:
        return self.data.config["stamp"] if self.data.config else None

    async def inject_configuration(self, clusters: list[str],
                                   comment: str = "") -> dict:
        """Replace the active multi-cluster configuration
        (MultiClusterOracle.InjectMultiClusterConfiguration): timestamped,
        last-writer-wins, gossiped immediately so peers converge within
        one channel round-trip. Returns the injected config."""
        clusters = sorted(set(clusters))
        if not clusters:
            raise ValueError("multi-cluster configuration must name at "
                             "least one cluster")
        cfg = {"clusters": clusters, "stamp": time.time(),
               "comment": comment}
        if self.data.config and cfg["stamp"] <= self.data.config["stamp"]:
            # same-clock-tick re-injection still must win LWW
            cfg["stamp"] = self.data.config["stamp"] + 1e-6
        self.data.config = cfg
        self._fire_config_listeners()
        await self.gossip_once()
        return dict(cfg)

    # -- queries ---------------------------------------------------------
    def active_config(self) -> dict | None:
        return dict(self.data.config) if self.data.config else None

    def known_clusters(self) -> list[str]:
        """The multi-cluster network's member set: the admin-injected
        configuration when one exists (the reference's conf-governed
        membership), else everything gossip has merged (zero-conf mode).
        A configured-but-never-seen cluster is still listed — its
        gateways just resolve empty until it gossips."""
        if self.data.config is not None:
            return list(self.data.config["clusters"])
        return sorted(self.data.clusters)

    def gateways_of(self, cluster_id: str) -> list[SiloAddress]:
        entry = self.data.clusters.get(cluster_id)
        return list(entry["gateways"]) if entry else []


def add_multicluster(builder, cluster_id: str, channels: list,
                     gossip_period: float = 1.0, gsi: bool = True,
                     maintainer_period: float = 1.0):
    """Install a gossip oracle on a SiloBuilder (silo.multicluster), plus —
    unless ``gsi=False`` — the Global-Single-Instance runtime: the
    per-cluster directory grain, the cross-cluster gateway bridge, and the
    Doubtful-retry maintainer (silo.gsi)."""
    if gsi:
        from .gsi import cluster_directory_grain_class
        builder.add_grains(cluster_directory_grain_class())

    def install(silo) -> None:
        oracle = MultiClusterOracle(silo, cluster_id, channels, gossip_period)
        silo.multicluster = oracle
        from ..runtime.silo import ServiceLifecycleStage
        silo.subscribe_lifecycle(
            ServiceLifecycleStage.RUNTIME_GRAIN_SERVICES,
            oracle.start, oracle.stop)
        if gsi:
            from .gsi import GsiRuntime
            runtime = GsiRuntime(silo, oracle,
                                 maintainer_period=maintainer_period)
            silo.gsi = runtime
            silo.subscribe_lifecycle(
                ServiceLifecycleStage.RUNTIME_GRAIN_SERVICES,
                runtime.start, runtime.stop)

    return builder.configure(install)
