"""Multi-cluster gossip: configuration + gateway exchange between clusters.

Re-design of /root/reference/src/Orleans.Runtime/MultiClusterNetwork/
MultiClusterOracle.cs:12 + MultiClusterGossipChannelFactory.cs: each cluster
periodically merges its local view (its own gateways, stamped) with one or
more gossip channels — last-writer-wins per cluster key. Channels are
pluggable (the factory's job): in-memory for tests, JSON-file and sqlite
for real multi-process deployments (the Azure-table channel stand-ins,
mirroring the membership-table backend split).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import sqlite3
import threading
import time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING

from ..core.ids import SiloAddress

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.multicluster")

__all__ = ["MultiClusterData", "InMemoryGossipChannel", "FileGossipChannel",
           "SqliteGossipChannel", "MultiClusterOracle"]


@dataclass
class MultiClusterData:
    """Gossiped payload (MultiClusterData): per-cluster gateway lists +
    stamps; merge = per-key newest stamp wins."""

    clusters: dict[str, dict] = field(default_factory=dict)
    # clusters[cluster_id] = {"gateways": [SiloAddress], "stamp": float}

    def merge(self, other: "MultiClusterData") -> bool:
        changed = False
        for cid, entry in other.clusters.items():
            mine = self.clusters.get(cid)
            if mine is None or entry["stamp"] > mine["stamp"]:
                self.clusters[cid] = dict(entry)
                changed = True
        return changed

    def copy(self) -> "MultiClusterData":
        return MultiClusterData({k: dict(v) for k, v in self.clusters.items()})


class GossipChannel:
    """Shared gossip substrate (IGossipChannel)."""

    async def publish(self, data: MultiClusterData) -> None:
        raise NotImplementedError

    async def read(self) -> MultiClusterData:
        raise NotImplementedError


class InMemoryGossipChannel(GossipChannel):
    """Dev/test channel: one shared object across clusters (the Azure-table
    stand-in)."""

    def __init__(self) -> None:
        self._data = MultiClusterData()

    async def publish(self, data: MultiClusterData) -> None:
        self._data.merge(data)

    async def read(self) -> MultiClusterData:
        return self._data.copy()


def _data_to_json(data: MultiClusterData) -> dict:
    return {cid: {"stamp": e["stamp"],
                  "gateways": [[g.host, g.port, g.generation, g.mesh_index]
                               for g in e["gateways"]]}
            for cid, e in data.clusters.items()}


def _data_from_json(raw: dict) -> MultiClusterData:
    return MultiClusterData({
        cid: {"stamp": e["stamp"],
              "gateways": [SiloAddress(h, p, g, m)
                           for h, p, g, m in e["gateways"]]}
        for cid, e in raw.items()})


class FileGossipChannel(GossipChannel):
    """Cross-process channel: one JSON file shared by all clusters.
    Publish is read-merge-write with an atomic replace; the merge is
    commutative and idempotent (per-cluster last-writer-wins), so a lost
    race between two processes heals on the next gossip round."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = asyncio.Lock()

    def _load(self) -> MultiClusterData:
        try:
            with open(self.path, encoding="utf-8") as f:
                return _data_from_json(json.load(f))
        except (FileNotFoundError, json.JSONDecodeError):
            return MultiClusterData()

    async def publish(self, data: MultiClusterData) -> None:
        def write() -> None:
            merged = self._load()
            merged.merge(data)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(_data_to_json(merged), f)
            os.replace(tmp, self.path)

        async with self._lock:  # file I/O off the loop (like the sqlite
            # sibling): a slow/network filesystem must not stall turns
            await asyncio.get_running_loop().run_in_executor(None, write)

    async def read(self) -> MultiClusterData:
        async with self._lock:
            return await asyncio.get_running_loop().run_in_executor(
                None, self._load)


class SqliteGossipChannel(GossipChannel):
    """Cross-process channel over sqlite (real database locking): one row
    per cluster id, last-writer-wins by stamp."""

    def __init__(self, path: str) -> None:
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._dblock = threading.Lock()
        with self._dblock:
            self._db.execute("PRAGMA journal_mode=WAL")
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS gossip ("
                " cluster TEXT PRIMARY KEY, stamp REAL, gateways TEXT)")
            self._db.commit()

    def close(self) -> None:
        with self._dblock:
            self._db.close()

    async def publish(self, data: MultiClusterData) -> None:
        def write() -> None:
            with self._dblock:
                for cid, e in data.clusters.items():
                    row = self._db.execute(
                        "SELECT stamp FROM gossip WHERE cluster=?",
                        (cid,)).fetchone()
                    if row is None or e["stamp"] > row[0]:
                        self._db.execute(
                            "INSERT OR REPLACE INTO gossip VALUES (?,?,?)",
                            (cid, e["stamp"], json.dumps(
                                [[g.host, g.port, g.generation, g.mesh_index]
                                 for g in e["gateways"]])))
                self._db.commit()

        await asyncio.get_running_loop().run_in_executor(None, write)

    async def read(self) -> MultiClusterData:
        def load() -> MultiClusterData:
            with self._dblock:
                rows = self._db.execute(
                    "SELECT cluster, stamp, gateways FROM gossip").fetchall()
            return MultiClusterData({
                cid: {"stamp": stamp,
                      "gateways": [SiloAddress(h, p, g, m)
                                   for h, p, g, m in json.loads(gws)]}
                for cid, stamp, gws in rows})

        return await asyncio.get_running_loop().run_in_executor(None, load)


class MultiClusterOracle:
    """Per-silo gossip oracle; silos of one cluster share a cluster_id."""

    def __init__(self, silo: "Silo", cluster_id: str,
                 channels: list[GossipChannel],
                 gossip_period: float = 1.0):
        self.silo = silo
        self.cluster_id = cluster_id
        self.channels = channels
        self.gossip_period = gossip_period
        self.data = MultiClusterData()
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            try:
                await self.gossip_once()
            except Exception:  # noqa: BLE001
                log.exception("gossip round failed")
            await asyncio.sleep(self.gossip_period)

    async def gossip_once(self) -> None:
        """One round: stamp our view, merge every channel, publish back."""
        self.data.clusters[self.cluster_id] = {
            "gateways": list(self.silo.locator.alive_list),
            "stamp": time.time(),
        }
        for ch in self.channels:
            remote = await ch.read()
            self.data.merge(remote)
            await ch.publish(self.data)

    # -- queries ---------------------------------------------------------
    def known_clusters(self) -> list[str]:
        return sorted(self.data.clusters)

    def gateways_of(self, cluster_id: str) -> list[SiloAddress]:
        entry = self.data.clusters.get(cluster_id)
        return list(entry["gateways"]) if entry else []


def add_multicluster(builder, cluster_id: str, channels: list,
                     gossip_period: float = 1.0, gsi: bool = True,
                     maintainer_period: float = 1.0):
    """Install a gossip oracle on a SiloBuilder (silo.multicluster), plus —
    unless ``gsi=False`` — the Global-Single-Instance runtime: the
    per-cluster directory grain, the cross-cluster gateway bridge, and the
    Doubtful-retry maintainer (silo.gsi)."""
    if gsi:
        from .gsi import cluster_directory_grain_class
        builder.add_grains(cluster_directory_grain_class())

    def install(silo) -> None:
        oracle = MultiClusterOracle(silo, cluster_id, channels, gossip_period)
        silo.multicluster = oracle
        from ..runtime.silo import ServiceLifecycleStage
        silo.subscribe_lifecycle(
            ServiceLifecycleStage.RUNTIME_GRAIN_SERVICES,
            oracle.start, oracle.stop)
        if gsi:
            from .gsi import GsiRuntime
            runtime = GsiRuntime(silo, oracle,
                                 maintainer_period=maintainer_period)
            silo.gsi = runtime
            silo.subscribe_lifecycle(
                ServiceLifecycleStage.RUNTIME_GRAIN_SERVICES,
                runtime.start, runtime.stop)

    return builder.configure(install)
