"""Global-Single-Instance (GSI) registration protocol.

Re-design of /root/reference/src/Orleans.Runtime/GrainDirectory/
MultiClusterRegistration/: ``GlobalSingleInstanceRegistrar.cs`` +
``ClusterGrainDirectory.cs:86-140`` — ownership states
RequestedOwnership/Owned/Doubtful/Cached/RaceLoser with lexicographic race
resolution, and ``GlobalSingleInstanceActivationMaintainer`` retrying
Doubtful entries.

The cross-cluster query is abstracted as ``peer_query(cluster_id, grain_id)
-> (state, owner_cluster)``; in-proc multi-fabric tests bind it directly,
a DCN deployment binds it to remote cluster-gateway calls.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
from dataclasses import dataclass
from enum import Enum
from typing import Awaitable, Callable

from ..core.ids import GrainId

log = logging.getLogger("orleans.multicluster.gsi")

__all__ = ["GsiState", "GsiEntry", "GlobalSingleInstanceRegistrar"]


class GsiState(str, Enum):
    """Ownership states (ActivationStatus in the reference protocol)."""

    REQUESTED_OWNERSHIP = "RequestedOwnership"
    OWNED = "Owned"
    DOUBTFUL = "Doubtful"
    CACHED = "Cached"
    RACE_LOSER = "RaceLoser"


@dataclass
class GsiEntry:
    grain_id: GrainId
    state: GsiState
    owner_cluster: str


PeerQuery = Callable[[str, GrainId], Awaitable[tuple[GsiState | None, str | None]]]


class GlobalSingleInstanceRegistrar:
    """One per cluster: decides cluster-level ownership of grain ids."""

    def __init__(self, cluster_id: str, known_clusters: Callable[[], list[str]],
                 peer_query: PeerQuery):
        self.cluster_id = cluster_id
        self.known_clusters = known_clusters
        self.peer_query = peer_query
        self.entries: dict[GrainId, GsiEntry] = {}

    def status_of(self, grain_id: GrainId) -> tuple[GsiState | None, str | None]:
        """The remote-query surface (ClusterGrainDirectory.ProcessRequest)."""
        e = self.entries.get(grain_id)
        return (e.state, e.owner_cluster) if e else (None, None)

    async def register(self, grain_id: GrainId) -> GsiEntry:
        """Try to own ``grain_id`` globally (GSI protocol rounds):

        1. mark RequestedOwnership locally;
        2. query every other cluster;
        3. any OWNED elsewhere → we become CACHED at that owner;
           a concurrent RequestedOwnership elsewhere → lexicographically
           smaller cluster id wins, loser becomes RACE_LOSER then CACHED;
           peers unreachable → DOUBTFUL (owned-but-retry, maintainer job).
        """
        cur = self.entries.get(grain_id)
        if cur is not None and cur.state in (GsiState.OWNED, GsiState.CACHED):
            return cur
        entry = GsiEntry(grain_id, GsiState.REQUESTED_OWNERSHIP,
                         self.cluster_id)
        self.entries[grain_id] = entry
        peers = [c for c in self.known_clusters() if c != self.cluster_id]
        unreachable = False
        for peer in peers:
            try:
                state, owner = await self.peer_query(peer, grain_id)
            except Exception:  # noqa: BLE001
                unreachable = True
                continue
            if state == GsiState.OWNED:
                entry.state = GsiState.CACHED
                entry.owner_cluster = owner or peer
                return entry
            if state == GsiState.REQUESTED_OWNERSHIP:
                # simultaneous race: lexicographic winner
                if peer < self.cluster_id:
                    entry.state = GsiState.RACE_LOSER
                    entry.owner_cluster = peer
                    # loser re-queries later; the winner transitions to OWNED
                    return entry
        entry.state = GsiState.DOUBTFUL if unreachable else GsiState.OWNED
        entry.owner_cluster = self.cluster_id
        return entry

    async def retry_doubtful(self) -> list[GrainId]:
        """GlobalSingleInstanceActivationMaintainer: re-run the protocol for
        Doubtful and RaceLoser entries. Returns the grain ids that ceded
        ownership (became CACHED) — their local activations must die."""
        ceded: list[GrainId] = []
        for gid, e in list(self.entries.items()):
            if e.state in (GsiState.DOUBTFUL, GsiState.RACE_LOSER):
                del self.entries[gid]
                new = await self.register(gid)
                if new.state == GsiState.CACHED:
                    ceded.append(gid)
        return ceded

    def unregister(self, grain_id: GrainId) -> None:
        self.entries.pop(grain_id, None)


# ---------------------------------------------------------------------------
# Cluster-level wiring: the directory grain, the cross-cluster bridge, the
# Doubtful-retry maintainer, and incoming-call forwarding
# ---------------------------------------------------------------------------

def global_single_instance(cls: type) -> type:
    """Class decorator: one activation of each key across ALL clusters
    ([GlobalSingleInstance]). Calls arriving in a non-owner cluster are
    forwarded to the owner cluster's gateway (return-to-origin forwarding,
    Dispatcher.cs:534-546)."""
    cls.__orleans_global_single_instance__ = True
    return cls


def _make_grain_base():
    """Build the per-cluster directory grain (one activation, key="gsi"):
    authoritative GSI ownership state + the grain-call surface remote
    clusters query (ClusterGrainDirectory.cs:86-140). Built lazily to
    avoid a module import cycle with the runtime.

    The ownership map is the protocol's truth, so it must not vanish with
    an idle sweep or a host-silo death: the grain is pinned against idle
    collection AND persists its entries (StatefulGrain) — a reactivation
    anywhere rebuilds the registrar from storage before answering."""
    from ..runtime.grain import StatefulGrain, collection_age

    from ..runtime.grain import reentrant

    # Reentrant like the reference's interleaving ClusterGrainDirectory
    # SystemTarget: acquire() awaits cross-cluster peer queries, and two
    # clusters' simultaneous first-touches would otherwise deadlock each
    # other's directory turns into response-timeout DOUBTFULs (duplicate
    # owners on a healthy network).
    @reentrant
    @collection_age(10 * 365 * 24 * 3600.0)   # pinned: never idle-collect
    class _ClusterDirectoryGrain(StatefulGrain):
        def _registrar_ref(self) -> GlobalSingleInstanceRegistrar:
            reg = getattr(self, "_registrar", None)
            if reg is None:
                gsi = self._activation.runtime.gsi
                reg = self._registrar = GlobalSingleInstanceRegistrar(
                    gsi.cluster_id, gsi.known_clusters, gsi.peer_query)
                for gid, state, owner in self.state.get("entries", []):
                    reg.entries[gid] = GsiEntry(gid, GsiState(state), owner)
            return reg

        async def _persist(self) -> None:
            reg = self._registrar_ref()
            self.state["entries"] = [
                (gid, e.state.value, e.owner_cluster)
                for gid, e in reg.entries.items()]
            try:
                await self.write_state()
            except Exception:  # noqa: BLE001 — best-effort durability;
                # in-memory state still serves until the next mutation
                log.exception("GSI directory persist failed")

        async def acquire(self, grain_id: GrainId) -> tuple[str, str]:
            reg = self._registrar_ref()
            before = reg.entries.get(grain_id)
            e = await reg.register(grain_id)
            if before is None or before.state != e.state:
                await self._persist()
            return (e.state.value, e.owner_cluster)

        async def status(self, grain_id: GrainId
                         ) -> tuple[str | None, str | None]:
            state, owner = self._registrar_ref().status_of(grain_id)
            return (state.value if state else None, owner)

        async def release(self, grain_id: GrainId) -> None:
            self._registrar_ref().unregister(grain_id)
            await self._persist()

        async def retry_doubtful(self) -> list:
            reg = self._registrar_ref()
            had_doubt = any(e.state in (GsiState.DOUBTFUL,
                                        GsiState.RACE_LOSER)
                            for e in reg.entries.values())
            ceded = await reg.retry_doubtful()
            if had_doubt:
                await self._persist()
            return ceded

        async def cached_grains(self) -> list:
            """Grain ids this cluster holds as CACHED (owned elsewhere) —
            the maintainer's duplicate-deactivation sweep input."""
            return [gid for gid, e in self._registrar_ref().entries.items()
                    if e.state == GsiState.CACHED]

        async def demote_removed_owners(self, active: list) -> int:
            """Admin-config removal semantics: entries whose owner
            cluster was removed from the multi-cluster configuration
            become DOUBTFUL, so the maintainer re-runs the protocol
            against the REMAINING clusters and the grains re-home
            (typically to this cluster, now that the old owner is no
            longer queried). Entries we own ourselves are untouched."""
            reg = self._registrar_ref()
            active_set = set(active)
            demoted = 0
            for e in reg.entries.values():
                # CACHED/RACE_LOSER only: already-Doubtful entries are the
                # maintainer's job regardless — recounting them here would
                # re-persist and re-log on every later config event
                if e.owner_cluster != reg.cluster_id \
                        and e.owner_cluster not in active_set \
                        and e.state in (GsiState.CACHED,
                                        GsiState.RACE_LOSER):
                    e.state = GsiState.DOUBTFUL
                    demoted += 1
            if demoted:
                await self._persist()
            return demoted

    _ClusterDirectoryGrain.__name__ = "ClusterDirectoryGrain"
    return _ClusterDirectoryGrain


_grain_cls_cache: list = []


def cluster_directory_grain_class() -> type:
    if not _grain_cls_cache:
        _grain_cls_cache.append(_make_grain_base())
    return _grain_cls_cache[0]


class GsiRuntime:
    """Per-silo GSI services (installed as ``silo.gsi``): the cross-cluster
    peer-query bridge over cluster gateways, an incoming-call decision
    cache, and the Doubtful-retry maintainer
    (GlobalSingleInstanceActivationMaintainer)."""

    def __init__(self, silo, oracle, maintainer_period: float = 1.0):
        self.silo = silo
        self.oracle = oracle
        self.cluster_id = oracle.cluster_id
        self.maintainer_period = maintainer_period
        self._clients: dict[str, object] = {}   # cluster_id -> GatewayClient
        self._client_locks: dict[str, asyncio.Lock] = {}
        self._maintainer: asyncio.Task | None = None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        if self._maintainer is None:
            self._maintainer = asyncio.get_running_loop().create_task(
                self._maintainer_loop())
        if self._on_config not in self.oracle.config_listeners:
            self.oracle.config_listeners.append(self._on_config)

    async def stop(self) -> None:
        if self._maintainer is not None:
            self._maintainer.cancel()
            self._maintainer = None
        with contextlib.suppress(ValueError):
            self.oracle.config_listeners.remove(self._on_config)
        for c in self._clients.values():
            try:
                # close_async tears down the reconnect loop + sockets;
                # the sync close() only breaks pending callbacks
                await c.close_async()
            except Exception:  # noqa: BLE001
                pass
        self._clients.clear()

    def known_clusters(self) -> list[str]:
        return self.oracle.known_clusters()

    def _on_config(self, config: dict) -> None:
        """A new admin configuration landed (injected here or learned via
        gossip): demote GSI entries owned by removed clusters so the
        maintainer re-homes them, and drop cached gateway clients to
        clusters no longer in the network."""
        if config is None:
            return
        active = list(config["clusters"])
        loop = asyncio.get_running_loop()

        async def apply() -> None:
            for cid in [c for c in self._clients if c not in active]:
                client = self._clients.pop(cid, None)
                if client is not None:
                    try:
                        await client.close_async()
                    except Exception:  # noqa: BLE001
                        pass
            if self.silo.status != "Running":
                return
            try:
                n = await self._directory().demote_removed_owners(active)
                if n:
                    log.info("multicluster config change: %d GSI entries "
                             "demoted to Doubtful for re-homing", n)
            except Exception:  # noqa: BLE001
                log.exception("removed-owner demotion failed")

        loop.create_task(apply())

    # -- local directory surface -----------------------------------------
    def _directory(self):
        return self.silo.grain_factory.get_grain(
            cluster_directory_grain_class(), "gsi")

    async def acquire(self, grain_id: GrainId) -> tuple[str, str]:
        return tuple(await self._directory().acquire(grain_id))

    async def status(self, grain_id: GrainId):
        return tuple(await self._directory().status(grain_id))

    # -- cross-cluster bridge --------------------------------------------
    async def _client_for(self, cluster_id: str):
        client = self._clients.get(cluster_id)
        if client is not None and getattr(client, "connected", False):
            return client
        lock = self._client_locks.setdefault(cluster_id, asyncio.Lock())
        async with lock:  # dedup concurrent connects; one client per peer
            client = self._clients.get(cluster_id)
            if client is not None and getattr(client, "connected", False):
                return client
            if client is not None:
                try:  # replaced stale client: tear down its reconnector
                    await client.close_async()
                except Exception:  # noqa: BLE001
                    pass
                self._clients.pop(cluster_id, None)
            gateways = self.oracle.gateways_of(cluster_id)
            if not gateways:
                raise ConnectionError(f"no known gateways for {cluster_id}")
            from ..runtime.socket_fabric import GatewayClient
            client = GatewayClient([g.endpoint for g in gateways],
                                   response_timeout=5.0)
            await client.connect()
            self._clients[cluster_id] = client
            return client

    async def peer_query(self, cluster_id: str, grain_id: GrainId
                         ) -> tuple[GsiState | None, str | None]:
        """Query another cluster's directory over its gateway (the
        cross-cluster half of ClusterGrainDirectory.ProcessRequest)."""
        client = await self._client_for(cluster_id)
        state, owner = await client.get_grain(
            cluster_directory_grain_class(), "gsi").status(grain_id)
        return (GsiState(state) if state else None, owner)

    async def forward_call(self, owner_cluster: str, msg) -> object:
        """Return-to-origin forwarding: run the grain call in the owner
        cluster via its gateway and hand back the result."""
        client = await self._client_for(owner_cluster)
        args, kwargs = msg.body if msg.body is not None else ((), {})
        return await client.send_request(
            target_grain=msg.target_grain, grain_class=None,
            interface_name=msg.interface_name, method_name=msg.method_name,
            args=args, kwargs=kwargs)

    # -- maintainer ------------------------------------------------------
    async def _maintainer_loop(self) -> None:
        while True:
            await asyncio.sleep(self.maintainer_period)
            if self.silo.status != "Running":
                continue
            try:
                await self._directory().retry_doubtful()
                # duplicate-deactivation sweep: any LOCAL activation of a
                # grain the cluster directory marks CACHED (owned by
                # another cluster) lost an ownership race — it must die.
                # Every silo sweeps its own catalog, so duplicates die
                # wherever they live, not just on the silo whose poll
                # triggered the cede.
                for gid in await self._directory().cached_grains() or []:
                    for act in list(self.silo.catalog.by_grain.get(gid, [])):
                        self.silo.catalog.schedule_deactivation(act)
            except Exception:  # noqa: BLE001
                log.debug("GSI maintainer round failed", exc_info=True)
