"""Global-Single-Instance (GSI) registration protocol.

Re-design of /root/reference/src/Orleans.Runtime/GrainDirectory/
MultiClusterRegistration/: ``GlobalSingleInstanceRegistrar.cs`` +
``ClusterGrainDirectory.cs:86-140`` — ownership states
RequestedOwnership/Owned/Doubtful/Cached/RaceLoser with lexicographic race
resolution, and ``GlobalSingleInstanceActivationMaintainer`` retrying
Doubtful entries.

The cross-cluster query is abstracted as ``peer_query(cluster_id, grain_id)
-> (state, owner_cluster)``; in-proc multi-fabric tests bind it directly,
a DCN deployment binds it to remote cluster-gateway calls.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass
from enum import Enum
from typing import Awaitable, Callable

from ..core.ids import GrainId

log = logging.getLogger("orleans.multicluster.gsi")

__all__ = ["GsiState", "GsiEntry", "GlobalSingleInstanceRegistrar"]


class GsiState(str, Enum):
    """Ownership states (ActivationStatus in the reference protocol)."""

    REQUESTED_OWNERSHIP = "RequestedOwnership"
    OWNED = "Owned"
    DOUBTFUL = "Doubtful"
    CACHED = "Cached"
    RACE_LOSER = "RaceLoser"


@dataclass
class GsiEntry:
    grain_id: GrainId
    state: GsiState
    owner_cluster: str


PeerQuery = Callable[[str, GrainId], Awaitable[tuple[GsiState | None, str | None]]]


class GlobalSingleInstanceRegistrar:
    """One per cluster: decides cluster-level ownership of grain ids."""

    def __init__(self, cluster_id: str, known_clusters: Callable[[], list[str]],
                 peer_query: PeerQuery):
        self.cluster_id = cluster_id
        self.known_clusters = known_clusters
        self.peer_query = peer_query
        self.entries: dict[GrainId, GsiEntry] = {}

    def status_of(self, grain_id: GrainId) -> tuple[GsiState | None, str | None]:
        """The remote-query surface (ClusterGrainDirectory.ProcessRequest)."""
        e = self.entries.get(grain_id)
        return (e.state, e.owner_cluster) if e else (None, None)

    async def register(self, grain_id: GrainId) -> GsiEntry:
        """Try to own ``grain_id`` globally (GSI protocol rounds):

        1. mark RequestedOwnership locally;
        2. query every other cluster;
        3. any OWNED elsewhere → we become CACHED at that owner;
           a concurrent RequestedOwnership elsewhere → lexicographically
           smaller cluster id wins, loser becomes RACE_LOSER then CACHED;
           peers unreachable → DOUBTFUL (owned-but-retry, maintainer job).
        """
        cur = self.entries.get(grain_id)
        if cur is not None and cur.state in (GsiState.OWNED, GsiState.CACHED):
            return cur
        entry = GsiEntry(grain_id, GsiState.REQUESTED_OWNERSHIP,
                         self.cluster_id)
        self.entries[grain_id] = entry
        peers = [c for c in self.known_clusters() if c != self.cluster_id]
        unreachable = False
        for peer in peers:
            try:
                state, owner = await self.peer_query(peer, grain_id)
            except Exception:  # noqa: BLE001
                unreachable = True
                continue
            if state == GsiState.OWNED:
                entry.state = GsiState.CACHED
                entry.owner_cluster = owner or peer
                return entry
            if state == GsiState.REQUESTED_OWNERSHIP:
                # simultaneous race: lexicographic winner
                if peer < self.cluster_id:
                    entry.state = GsiState.RACE_LOSER
                    entry.owner_cluster = peer
                    # loser re-queries later; the winner transitions to OWNED
                    return entry
        entry.state = GsiState.DOUBTFUL if unreachable else GsiState.OWNED
        entry.owner_cluster = self.cluster_id
        return entry

    async def retry_doubtful(self) -> None:
        """GlobalSingleInstanceActivationMaintainer: re-run the protocol for
        Doubtful and RaceLoser entries."""
        for gid, e in list(self.entries.items()):
            if e.state in (GsiState.DOUBTFUL, GsiState.RACE_LOSER):
                del self.entries[gid]
                await self.register(gid)

    def unregister(self, grain_id: GrainId) -> None:
        self.entries.pop(grain_id, None)
