"""Multi-cluster / geo federation (reference src/Orleans.Runtime/
MultiClusterNetwork/ + GrainDirectory/MultiClusterRegistration/).

Gossip rides pluggable channels (in-memory / file / sqlite — the
Azure-table channel stand-ins) so clusters in separate processes
federate; the GSI ownership protocol runs over real cluster gateways
(GatewayClient over the socket fabric), with calls to remotely-owned
grains forwarded to the owner cluster and a Doubtful-retry maintainer
resolving partition-era ownership conflicts."""

from .gossip import (
    FileGossipChannel,
    InMemoryGossipChannel,
    MultiClusterData,
    MultiClusterOracle,
    SqliteGossipChannel,
    add_multicluster,
)
from .gsi import (
    GlobalSingleInstanceRegistrar,
    GsiRuntime,
    GsiState,
    cluster_directory_grain_class,
    global_single_instance,
)

__all__ = [
    "MultiClusterData", "InMemoryGossipChannel", "FileGossipChannel",
    "SqliteGossipChannel", "MultiClusterOracle", "add_multicluster",
    "GsiState", "GlobalSingleInstanceRegistrar", "GsiRuntime",
    "global_single_instance", "cluster_directory_grain_class",
]
