"""Multi-cluster / geo federation (reference src/Orleans.Runtime/
MultiClusterNetwork/ + GrainDirectory/MultiClusterRegistration/).

SURVEY §2.4 scopes geo replication as a design hook: this package carries
the working gossip oracle + the GSI ownership protocol over an abstract
cross-cluster channel; DCN transport binding is deferred."""

from .gossip import (
    InMemoryGossipChannel,
    MultiClusterData,
    MultiClusterOracle,
    add_multicluster,
)
from .gsi import (
    GsiState,
    GlobalSingleInstanceRegistrar,
)

__all__ = [
    "MultiClusterData", "InMemoryGossipChannel", "MultiClusterOracle",
    "add_multicluster", "GsiState", "GlobalSingleInstanceRegistrar",
]
