"""orleans_tpu — a TPU-native virtual-actor ("grain") framework.

A ground-up re-design of the Microsoft Orleans programming model
(reference at /root/reference, surveyed in SURVEY.md) for TPU hardware:
grain invocations are coalesced each tick into vectorized actor-update
kernels (jax/pjit/Pallas) over activation state sharded across the device
mesh, with cross-silo messages riding ICI collectives and the host running
the control plane (membership, placement, storage, client gateway).
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    # Honor an explicit CPU request: in this image the axon TPU plugin
    # registers regardless of JAX_PLATFORMS and would grab the tunnel; the
    # config update reliably pins CPU (tests/conftest.py and
    # __graft_entry__.py apply the same pin).
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")
