"""Typed options groups + validators (the reference's config system).

Re-design of /root/reference/src/Orleans.Core/Configuration/Options/*
(ClusterOptions, MessagingOptions, PerformanceTuningOptions, …), the
runtime-side groups (SiloMessagingOptions, SchedulingOptions,
GrainCollectionOptions — Runtime/Configuration/Options/), the validators
(Core/Configuration/Validators/) and the startup options dump
(Runtime/OptionsLogger/). The groups flatten into the runtime's flat
``SiloConfig`` view via :func:`flatten`; ``SiloBuilder.with_options``
consumes them fluently (the ``.Configure<XOptions>(...)`` idiom,
SiloHostBuilder.cs:13).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, fields

from .core.errors import ConfigurationError
from .runtime.silo import SiloConfig

log = logging.getLogger("orleans.options")

__all__ = [
    "ClusterOptions", "MessagingOptions", "SchedulingOptions",
    "GrainCollectionOptions", "MembershipOptions", "DirectoryOptions",
    "LoadSheddingOptions", "DispatchOptions", "RebalanceOptions",
    "TracingOptions", "MetricsOptions", "ProfilingOptions", "SloOptions",
    "StreamOptions", "LedgerOptions",
    "flatten", "apply_options", "validate_options", "log_options",
]


def _positive(opts, *names: str) -> None:
    for n in names:
        v = getattr(opts, n)
        if not (isinstance(v, (int, float)) and v > 0):
            raise ConfigurationError(
                f"{type(opts).__name__}.{n} must be > 0, got {v!r}")


@dataclass
class ClusterOptions:
    """ClusterOptions (Core/Configuration/Options/ClusterOptions.cs):
    cluster/service identity."""

    cluster_id: str = "default"
    service_id: str = "default"

    def validate(self) -> None:
        if not self.cluster_id or not self.service_id:
            raise ConfigurationError(
                "cluster_id and service_id must be non-empty "
                "(ClusterOptionsValidator semantics)")


@dataclass
class MessagingOptions:
    """MessagingOptions / SiloMessagingOptions: timeouts, queue limits,
    stuck-turn age limit (MaxRequestProcessingTime), and the batched
    ingress pipeline switch (``batched_ingress=False`` restores the
    per-frame decode + per-message hand-off — the A/B lever; wire bytes
    are identical either way)."""

    response_timeout: float = 30.0
    max_enqueued_requests: int = 5000
    max_request_processing_time: float = 60.0
    batched_ingress: bool = True
    # multi-loop silo ingress (runtime.multiloop): N >= 2 spawns N
    # dedicated pump threads with their own event loops (sharded
    # ingress + SPSC hand-off rings, PING/SYSTEM bypassing the rings);
    # 1 (default) keeps the single-loop in-loop pump bit for bit
    ingress_loops: int = 1
    # sharded egress (runtime.multiloop.EgressShardPool): N >= 1 moves
    # silo-peer senders and shard-owned client-route response encode +
    # writev onto shard loops fed by SPSC egress rings (borrowing the
    # ingress shards when ingress_loops >= 2 — link-ownership
    # affinity — else dedicated egress loop threads); PING/SYSTEM
    # bypasses the rings per-message. 0 (default) keeps every sender
    # and encode on the main loop bit for bit — the A/B lever
    egress_shards: int = 0
    # batched response egress (runtime.egress flush accumulator +
    # header-prefix wire template): ``batched_egress=False`` restores
    # the per-message send_response → transmit path — the A/B lever
    # symmetric with ``batched_ingress``
    batched_egress: bool = True
    # off-loop device-tick pipeline (dispatch.engine tick worker):
    # ``offloop_tick=False`` restores the loop-inline tick — the A/B
    # lever paired with ``batched_ingress``
    offloop_tick: bool = True
    # multi-process silo (runtime.multiproc): N >= 2 forks N single-GIL
    # worker processes that each bind the SAME advertised endpoint via
    # SO_REUSEPORT (kernel accept balancing; a connection pins to its
    # accepting worker for life, so the multiloop per-grain FIFO
    # argument carries over verbatim). The device engine stays in the
    # owner process; workers feed vector calls through cross-process
    # SPSC staging rings on multiprocessing.shared_memory. 1 (default)
    # keeps the single-process path bit for bit — the A/B lever
    worker_procs: int = 1

    def validate(self) -> None:
        # no cross-field rule tying max_request_processing_time to
        # response_timeout: a stuck limit shorter than the caller timeout
        # is a legitimate fast-abandon configuration (the activation is
        # rebuilt while queued callers still wait within their timeout)
        _positive(self, "response_timeout", "max_enqueued_requests",
                  "max_request_processing_time", "ingress_loops")
        if not isinstance(self.ingress_loops, int) or \
                self.ingress_loops > 64:
            raise ConfigurationError(
                f"ingress_loops must be an int in [1, 64], got "
                f"{self.ingress_loops!r}")
        if not isinstance(self.egress_shards, int) or \
                isinstance(self.egress_shards, bool) or \
                not (0 <= self.egress_shards <= 64):
            raise ConfigurationError(
                f"egress_shards must be an int in [0, 64], got "
                f"{self.egress_shards!r}")
        if not isinstance(self.worker_procs, int) or \
                isinstance(self.worker_procs, bool) or \
                not (1 <= self.worker_procs <= 64):
            raise ConfigurationError(
                f"worker_procs must be an int in [1, 64], got "
                f"{self.worker_procs!r}")
        if self.worker_procs > 1 and self.ingress_loops > 1:
            raise ConfigurationError(
                "worker_procs > 1 and ingress_loops > 1 are mutually "
                "exclusive: each worker process is already a single-GIL "
                "silo (fork workers OR shard pump loops, not both)")


@dataclass
class SchedulingOptions:
    """SchedulingOptions: turn-length warning (TurnWarningLengthThreshold,
    OrleansTaskScheduler.cs:26) + deadlock detection
    (PerformDeadlockDetection)."""

    turn_warning_length: float = 0.2
    detect_deadlocks: bool = False

    def validate(self) -> None:
        _positive(self, "turn_warning_length")


@dataclass
class GrainCollectionOptions:
    """GrainCollectionOptions: idle-activation GC ages + quantum
    (ActivationCollector.cs:15)."""

    collection_age: float = 2 * 3600.0
    collection_quantum: float = 60.0
    deactivation_timeout: float = 5.0

    def validate(self) -> None:
        _positive(self, "collection_age", "collection_quantum",
                  "deactivation_timeout")
        if self.collection_age < self.collection_quantum:
            raise ConfigurationError(
                "collection_age must be >= collection_quantum "
                "(GrainCollectionOptionsValidator semantics)")


@dataclass
class MembershipOptions:
    """MembershipOptions (Core/Configuration/Options/MembershipOptions.cs):
    probe cadence, vote thresholds, refresh periods."""

    probe_period: float = 1.0
    probe_timeout: float = 1.0
    missed_probes_limit: int = 3
    votes_needed: int = 2
    num_probed: int = 3
    iam_alive_period: float = 5.0
    refresh_period: float = 5.0
    vote_expiration: float = 10.0

    def validate(self) -> None:
        _positive(self, "probe_period", "probe_timeout",
                  "missed_probes_limit", "votes_needed", "num_probed",
                  "iam_alive_period", "refresh_period", "vote_expiration")
        if self.votes_needed > self.num_probed + 1:
            raise ConfigurationError(
                f"votes_needed ({self.votes_needed}) can never be reached "
                f"with num_probed={self.num_probed} probers")


@dataclass
class LoadSheddingOptions:
    """LoadSheddingOptions: gateway ingress shed under overload. The
    reference sheds on CPU%; the host-tier analog sheds on application
    inbound queue depth — and, when ``queue_wait_limit`` > 0, on the
    WINDOWED ingest queue-wait trend (the INGEST_STATS backpressure
    signal fed from host turn starts and device batch starts): depth
    alone misses slow-drain overload where the queue stays short but
    every message waits long."""

    enabled: bool = False
    limit: int = 10_000
    # shed while the mean observed queue-wait over the last
    # ``queue_wait_window`` seconds exceeds this many seconds; 0 disables
    # the trend signal (depth-only, the pre-trend behavior)
    queue_wait_limit: float = 0.0
    queue_wait_window: float = 5.0

    def validate(self) -> None:
        _positive(self, "limit", "queue_wait_window")
        if self.queue_wait_limit < 0:
            raise ConfigurationError(
                "load shedding queue_wait_limit must be >= 0 "
                "(0 disables the trend signal)")


@dataclass
class DirectoryOptions:
    """Grain-directory caching (GrainDirectoryOptions: CachingStrategy,
    CacheSize; adaptive per-entry TTLs per
    AdaptiveGrainDirectoryCache.cs:178 + the maintainer's refresh loop,
    AdaptiveDirectoryCacheMaintainer.cs:243)."""

    cache_size: int = 100_000
    cache_initial_ttl: float = 5.0     # seconds; doubles on revalidation
    cache_max_ttl: float = 120.0
    cache_refresh_period: float = 2.0  # maintainer sweep; 0 disables

    def validate(self) -> None:
        _positive(self, "cache_size", "cache_initial_ttl", "cache_max_ttl")
        if self.cache_initial_ttl > self.cache_max_ttl:
            raise ConfigurationError(
                "directory cache_initial_ttl must be <= cache_max_ttl "
                f"(got {self.cache_initial_ttl} > {self.cache_max_ttl})")
        if self.cache_refresh_period < 0:
            raise ConfigurationError(
                "directory cache_refresh_period must be >= 0 "
                "(0 disables the maintainer)")


@dataclass
class RebalanceOptions:
    """Live activation migration & load-aware rebalancing
    (orleans_tpu.rebalance — the DeploymentLoadPublisher +
    activation-repartitioning trajectory of the reference): plan/execute
    cadence, per-round migration budget, and the imbalance hysteresis."""

    period: float = 0.0            # seconds between rounds; 0 disables
    budget: int = 8                # max migrations per round (both tiers)
    imbalance_ratio: float = 1.2   # rebalance only when hot > ratio * mean
    # consume the cost ledger's host-tier hot-actor candidates (ISSUE 17):
    # a grain whose charged seconds run hot against the per-key mean gets
    # a migration plan even when activation COUNTS are balanced — the
    # load signal counts alone cannot see. Requires ledger_enabled.
    use_ledger: bool = False

    def validate(self) -> None:
        _positive(self, "budget")
        if self.period < 0:
            raise ConfigurationError(
                "rebalance period must be >= 0 (0 disables the loop)")
        if self.imbalance_ratio < 1.0:
            raise ConfigurationError(
                "rebalance imbalance_ratio must be >= 1.0 — a threshold "
                "below the mean would migrate on every round forever")


@dataclass
class TracingOptions:
    """Distributed request tracing (observability.tracing): enable flag,
    head-based sampling rate (the ROOT of each trace rolls once; 0 keeps
    the collector installed but records nothing), and the per-silo span
    ring-buffer capacity.

    ``tail_*`` knobs enable tail-based retention: head sampling becomes a
    record-locally pre-filter and the keep/drop decision defers until the
    trace completes (root-span close + ``tail_window`` quiescence for
    straggler legs) — keep only slow (``tail_slow_threshold`` seconds
    absolute, and/or above ``tail_slow_percentile`` of recent roots),
    errored, or force-retained traces. ``tail_leg_ttl`` bounds how long a
    silo buffers legs of traces rooted elsewhere before expiring them
    un-pulled; ``tail_max_pending`` bounds the undecided-trace buffer.

    ``otlp_endpoint`` streams retained spans as OTLP/HTTP JSON to an
    OpenTelemetry collector (export.OtlpSink) in ``otlp_batch_size``
    batches flushed every ``otlp_flush_interval`` seconds; unset = no
    sink, and an unreachable collector degrades to counted drops."""

    enabled: bool = False
    sample_rate: float = 1.0
    buffer_size: int = 4096
    tail_enabled: bool = False
    tail_window: float = 0.25
    tail_slow_threshold: float = 0.1
    tail_slow_percentile: float = 0.0
    # auto-tune tail_slow_threshold from the root-duration percentile
    # history (LatencyErrorPolicy auto mode): the threshold converges on
    # the tail_slow_percentile cut (default 0.95 when unset), so drifting
    # baselines keep retaining the slowest ~(1-p) fraction
    tail_auto: bool = False
    tail_leg_ttl: float = 2.0
    tail_max_pending: int = 256
    otlp_endpoint: str | None = None
    otlp_batch_size: int = 64
    otlp_flush_interval: float = 0.5
    # ship OTLP bodies as protobuf wire bytes (application/x-protobuf)
    # instead of the JSON mapping; requires google.protobuf importable,
    # else the sink warns and keeps JSON
    otlp_protobuf: bool = False

    def validate(self) -> None:
        _positive(self, "buffer_size", "tail_window", "tail_leg_ttl",
                  "tail_max_pending", "otlp_batch_size",
                  "otlp_flush_interval")
        if not (0.0 <= self.sample_rate <= 1.0):
            raise ConfigurationError(
                f"trace sample_rate must be within [0, 1], got "
                f"{self.sample_rate!r}")
        if not (0.0 <= self.tail_slow_percentile < 1.0):
            raise ConfigurationError(
                f"trace tail_slow_percentile must be within [0, 1), got "
                f"{self.tail_slow_percentile!r}")
        if self.tail_slow_threshold < 0:
            raise ConfigurationError(
                "trace tail_slow_threshold must be >= 0 "
                "(0 disables the absolute threshold)")


@dataclass
class MetricsOptions:
    """Live metrics pipeline (observability.metrics — the reference's
    continuous statistics surface, Core/Statistics/ + LogStatistics):
    stage-level ingest instrumentation + the queue/backpressure sampler
    loop, the per-silo Prometheus pull endpoint, and periodic OTLP
    metrics push.

    ``enabled`` turns on the ingest stage histograms (decode / enqueue /
    queue-wait / staging / transfer / tick) and the sampler; everything
    costs one attribute check per site when off. ``port`` gates the
    stdlib-HTTP ``GET /metrics`` exposition endpoint (``None`` = no
    server; ``0`` = ephemeral port). ``otlp_endpoint`` streams registry
    snapshots every ``otlp_period`` seconds via export.OtlpMetricsSink
    (same bounded-queue/retry/drop discipline as trace export)."""

    enabled: bool = False
    sample_period: float = 1.0
    window: float = 60.0
    port: int | None = None
    otlp_endpoint: str | None = None
    otlp_period: float = 5.0
    # protobuf wire encoding for the metrics push (same gate/fallback as
    # the tracing sink's otlp_protobuf)
    otlp_protobuf: bool = False

    def validate(self) -> None:
        _positive(self, "sample_period", "window", "otlp_period")
        if self.port is not None and not (0 <= int(self.port) <= 65535):
            raise ConfigurationError(
                f"metrics port must be None or 0-65535, got {self.port!r}")


@dataclass
class ProfilingOptions:
    """Host-loop occupancy profiler + flight recorder
    (observability.profiling.LoopProfiler — the Watchdog/per-component
    cycle-stats analog of the reference, grown into continuous loop
    attribution): when ``enabled`` the silo interposes on its event
    loop's scheduling entry points and buckets every callback's wall
    time into named categories (turns / device tick schedule-staging-
    transfer-SYNC / pump / storage / observability / idle) in
    ``window``-second slices, keeping a ``ring``-deep flight ring with
    the ``top_k`` slowest callbacks per window. Anomalies (load shed,
    watchdog/sampler lag over ``lag_threshold``, queue-wait-trend
    breach, tail-retained traces) snapshot the ring, rate-limited to one
    per ``trigger_interval`` seconds per reason. Disabled: nothing is
    installed — the loop keeps its class methods."""

    enabled: bool = False
    window: float = 1.0
    ring: int = 120
    top_k: int = 8
    trigger_interval: float = 1.0
    lag_threshold: float = 0.25

    def validate(self) -> None:
        _positive(self, "window", "ring", "top_k", "trigger_interval",
                  "lag_threshold")


@dataclass
class SloOptions:
    """SLO engine (observability.slo — the judging layer over the
    metrics/tracing/profiling substrate): when ``enabled`` a per-silo
    :class:`~orleans_tpu.observability.slo.SloMonitor` evaluates the
    default objective set (app ingest latency, membership probe RTT,
    turn error rate, gateway shed rate — or a custom spec list set via
    ``silo.slo_specs``) every ``period`` seconds from interval-diffed
    registry snapshots, with Google-SRE multi-window burn-rate
    detection: breach when BOTH the ``fast_window`` and ``slow_window``
    burn the error budget faster than ``burn_threshold``× with at least
    ``min_events`` events in the fast window. A breach snapshots the
    flight recorder, force-retains in-flight tail traces, and bumps the
    ``slo.*`` counters/gauges; the cluster rolls up worst-burn-wins via
    ``ManagementGrain.get_cluster_slo``. Evaluation rides snapshot
    diffs — zero new hot-path instrumentation."""

    enabled: bool = False
    period: float = 1.0
    fast_window: float = 60.0
    slow_window: float = 300.0
    burn_threshold: float = 4.0
    min_events: int = 10
    # default-spec targets: latency = good fraction of ingest queue-wait
    # observations under latency_threshold seconds; probe = good fraction
    # of membership probe RTTs under the probe timeout; error/shed =
    # good fractions of turns/offered ingress
    latency_threshold: float = 0.1
    latency_target: float = 0.99
    probe_target: float = 0.99
    error_target: float = 0.999
    shed_target: float = 0.99
    # stream delivery latency (publish -> consumer-turn; fed from the
    # streams.delivery.seconds histogram the device provider observes)
    stream_target: float = 0.99
    stream_threshold: float = 0.25

    def validate(self) -> None:
        _positive(self, "period", "fast_window", "slow_window",
                  "burn_threshold", "min_events", "latency_threshold",
                  "stream_threshold")
        if self.fast_window >= self.slow_window:
            raise ConfigurationError(
                f"slo fast_window must be < slow_window "
                f"({self.fast_window} >= {self.slow_window}) — the slow "
                "window exists to CONFIRM what the fast window catches")
        for n in ("latency_target", "probe_target", "error_target",
                  "shed_target", "stream_target"):
            v = getattr(self, n)
            if not (0.0 < v < 1.0):
                raise ConfigurationError(
                    f"slo {n} must be in (0, 1), got {v!r} — a target of "
                    "1.0 leaves zero error budget")


@dataclass
class StreamOptions:
    """Device-tier streams (streams.device — the namespace fan-out
    compiled onto the bulk collectives): ``device_fanout`` arms the
    stream_fanout delivery lever on the persistent providers' vector
    path — dense bulk items ride broadcast edge exchanges instead of
    per-consumer call_batch ticks. OFF (default) keeps the per-consumer
    path bit for bit: the A/B lever, symmetric with ``batched_ingress``.
    ``device_cache_capacity`` bounds each device namespace's
    :class:`~orleans_tpu.streams.cache.PooledQueueCache` in batches
    (producers backpressure at 75% occupancy through the queue-wait-
    trend shed signal)."""

    device_fanout: bool = False
    device_cache_capacity: int = 1024

    def validate(self) -> None:
        _positive(self, "device_cache_capacity")


@dataclass
class LedgerOptions:
    """Cost-attribution ledger (observability.ledger — ISSUE 17): when
    ``enabled`` the silo charges every unit of work to (grain_class,
    method) × hashed-key × tenant — host-turn exec/queue seconds, device
    row-seconds, wire bytes per route, stream deliveries — with the
    per-key and per-tenant dimensions bounded by ``top_k`` space-saving
    sketches (exact class totals + overflow counter, deterministic
    cluster merge via ``ManagementGrain.get_cluster_ledger``).
    ``tenant_of`` maps a charge label ("Class/key") to its tenant;
    host-turn charges also read the caller's ``orleans.tenant``
    RequestContext baggage. OFF (default): ``silo.ledger`` is None and
    every charge site pays one attribute check — the A/B lever
    ``ping.bench_ledger_overhead`` floors."""

    enabled: bool = False
    top_k: int = 32
    tenant_of: object = None   # Callable[[str], str | None] | None

    def validate(self) -> None:
        _positive(self, "top_k")
        if self.tenant_of is not None and not callable(self.tenant_of):
            raise ConfigurationError(
                f"ledger tenant_of must be callable or None, got "
                f"{self.tenant_of!r}")


@dataclass
class DispatchOptions:
    """TPU vector-dispatch tier (no reference analog — the batched engine's
    knobs): per-shard slot-pool capacity and exchange lane capacity."""

    capacity_per_shard: int = 1024
    exchange_capacity: int = 256
    # off-loop tick worker for STANDALONE VectorRuntime(options=...)
    # construction (silo-hosted runtimes take the lever from
    # SiloConfig.offloop_tick / MessagingOptions.offloop_tick instead).
    # Default False: a bare engine keeps today's synchronous loop-inline
    # tick, which direct drivers (tests, bulk benchmarks) rely on.
    offloop_tick: bool = False

    def validate(self) -> None:
        _positive(self, "capacity_per_shard", "exchange_capacity")


# flat SiloConfig field ← (options group, group field)
_FLAT_MAP = {
    "cluster_id": (ClusterOptions, "cluster_id"),
    "service_id": (ClusterOptions, "service_id"),
    "response_timeout": (MessagingOptions, "response_timeout"),
    "max_enqueued_requests": (MessagingOptions, "max_enqueued_requests"),
    "max_request_processing_time": (MessagingOptions,
                                    "max_request_processing_time"),
    "batched_ingress": (MessagingOptions, "batched_ingress"),
    "ingress_loops": (MessagingOptions, "ingress_loops"),
    "egress_shards": (MessagingOptions, "egress_shards"),
    "worker_procs": (MessagingOptions, "worker_procs"),
    "batched_egress": (MessagingOptions, "batched_egress"),
    "offloop_tick": (MessagingOptions, "offloop_tick"),
    "turn_warning_length": (SchedulingOptions, "turn_warning_length"),
    "detect_deadlocks": (SchedulingOptions, "detect_deadlocks"),
    "collection_age": (GrainCollectionOptions, "collection_age"),
    "collection_quantum": (GrainCollectionOptions, "collection_quantum"),
    "deactivation_timeout": (GrainCollectionOptions, "deactivation_timeout"),
    "membership_probe_period": (MembershipOptions, "probe_period"),
    "membership_probe_timeout": (MembershipOptions, "probe_timeout"),
    "membership_missed_probes_limit": (MembershipOptions,
                                       "missed_probes_limit"),
    "membership_votes_needed": (MembershipOptions, "votes_needed"),
    "membership_num_probed": (MembershipOptions, "num_probed"),
    "membership_iam_alive_period": (MembershipOptions, "iam_alive_period"),
    "membership_refresh_period": (MembershipOptions, "refresh_period"),
    "membership_vote_expiration": (MembershipOptions, "vote_expiration"),
    "directory_cache_size": (DirectoryOptions, "cache_size"),
    "directory_cache_initial_ttl": (DirectoryOptions, "cache_initial_ttl"),
    "directory_cache_max_ttl": (DirectoryOptions, "cache_max_ttl"),
    "directory_cache_refresh_period": (DirectoryOptions,
                                       "cache_refresh_period"),
    "load_shedding_enabled": (LoadSheddingOptions, "enabled"),
    "load_shedding_limit": (LoadSheddingOptions, "limit"),
    "load_shedding_queue_wait": (LoadSheddingOptions, "queue_wait_limit"),
    "load_shedding_window": (LoadSheddingOptions, "queue_wait_window"),
    "rebalance_period": (RebalanceOptions, "period"),
    "rebalance_budget": (RebalanceOptions, "budget"),
    "rebalance_imbalance_ratio": (RebalanceOptions, "imbalance_ratio"),
    "rebalance_use_ledger": (RebalanceOptions, "use_ledger"),
    "trace_enabled": (TracingOptions, "enabled"),
    "trace_sample_rate": (TracingOptions, "sample_rate"),
    "trace_buffer_size": (TracingOptions, "buffer_size"),
    "trace_tail_enabled": (TracingOptions, "tail_enabled"),
    "trace_tail_window": (TracingOptions, "tail_window"),
    "trace_tail_slow_threshold": (TracingOptions, "tail_slow_threshold"),
    "trace_tail_slow_percentile": (TracingOptions, "tail_slow_percentile"),
    "trace_tail_auto": (TracingOptions, "tail_auto"),
    "trace_tail_leg_ttl": (TracingOptions, "tail_leg_ttl"),
    "trace_tail_max_pending": (TracingOptions, "tail_max_pending"),
    "trace_otlp_endpoint": (TracingOptions, "otlp_endpoint"),
    "trace_otlp_batch_size": (TracingOptions, "otlp_batch_size"),
    "trace_otlp_flush_interval": (TracingOptions, "otlp_flush_interval"),
    "trace_otlp_protobuf": (TracingOptions, "otlp_protobuf"),
    "metrics_enabled": (MetricsOptions, "enabled"),
    "metrics_sample_period": (MetricsOptions, "sample_period"),
    "metrics_window": (MetricsOptions, "window"),
    "metrics_port": (MetricsOptions, "port"),
    "metrics_otlp_endpoint": (MetricsOptions, "otlp_endpoint"),
    "metrics_otlp_period": (MetricsOptions, "otlp_period"),
    "metrics_otlp_protobuf": (MetricsOptions, "otlp_protobuf"),
    "slo_enabled": (SloOptions, "enabled"),
    "slo_period": (SloOptions, "period"),
    "slo_fast_window": (SloOptions, "fast_window"),
    "slo_slow_window": (SloOptions, "slow_window"),
    "slo_burn_threshold": (SloOptions, "burn_threshold"),
    "slo_min_events": (SloOptions, "min_events"),
    "slo_latency_threshold": (SloOptions, "latency_threshold"),
    "slo_latency_target": (SloOptions, "latency_target"),
    "slo_probe_target": (SloOptions, "probe_target"),
    "slo_error_target": (SloOptions, "error_target"),
    "slo_shed_target": (SloOptions, "shed_target"),
    "slo_stream_target": (SloOptions, "stream_target"),
    "slo_stream_threshold": (SloOptions, "stream_threshold"),
    "stream_device_fanout": (StreamOptions, "device_fanout"),
    "stream_device_cache_capacity": (StreamOptions,
                                     "device_cache_capacity"),
    "ledger_enabled": (LedgerOptions, "enabled"),
    "ledger_top_k": (LedgerOptions, "top_k"),
    "ledger_tenant_of": (LedgerOptions, "tenant_of"),
    "profiling_enabled": (ProfilingOptions, "enabled"),
    "profiling_window": (ProfilingOptions, "window"),
    "profiling_ring": (ProfilingOptions, "ring"),
    "profiling_top_k": (ProfilingOptions, "top_k"),
    "profiling_trigger_interval": (ProfilingOptions, "trigger_interval"),
    "profiling_lag_threshold": (ProfilingOptions, "lag_threshold"),
}


def validate_options(*groups) -> None:
    """Run every group's validator (the IConfigurationValidator pass the
    silo runs before start — DefaultSiloServices registers one per group)."""
    for g in groups:
        g.validate()


def flatten(*groups, name: str = "silo") -> SiloConfig:
    """Validate + flatten typed groups into the runtime's ``SiloConfig``.
    Unspecified groups keep their defaults."""
    return apply_options(SiloConfig(name=name), *groups)


def log_options(*groups, logger: logging.Logger | None = None) -> None:
    """Dump every option value at startup (Runtime/OptionsLogger/ — the
    reference logs all bound options when the silo boots)."""
    lg = logger or log
    for g in groups:
        for f in fields(g):
            lg.info("%s.%s = %r", type(g).__name__, f.name,
                    getattr(g, f.name))


def apply_options(cfg: SiloConfig, *groups) -> SiloConfig:
    """Validate the groups and overlay their values on a flat config
    (consumed by ``SiloBuilder.with_options``). Groups the silo config
    does not consume are rejected, never silently dropped."""
    validate_options(*groups)
    silo_groups = {cls for cls, _ in _FLAT_MAP.values()}
    for g in groups:
        if type(g) not in silo_groups:
            hint = (" — DispatchOptions configures the device tier; pass "
                    "it to VectorRuntime(options=...)"
                    if isinstance(g, DispatchOptions) else "")
            raise ConfigurationError(
                f"{type(g).__name__} is not consumed by the silo "
                f"config{hint}")
    by_type = {type(g): g for g in groups}
    for flat_field, (group_cls, group_field) in _FLAT_MAP.items():
        g = by_type.get(group_cls)
        if g is not None:
            setattr(cfg, flat_field, getattr(g, group_field))
    return cfg
