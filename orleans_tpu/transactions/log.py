"""Durable transaction commit log.

Re-design of /root/reference/src/Orleans.Transactions/TransactionLog.cs
(storage-backed commit log the TM appends decisions to before announcing
them) behind a pluggable provider interface, with in-memory / append-only
file / sqlite backends — the same provider split the membership table and
reminder table use (cloud log storage such as
Orleans.Transactions.AzureStorage maps to the File/Sqlite backends here;
no cloud egress in scope).

The log is the TM's durable truth: a decision is COMMITTED the moment its
record is appended, before any participant hears the outcome. A TM
activation replays the log on activate (seq + decision map), which is what
makes TM failover safe: in-doubt participants query ``decision_of`` against
the recovered map.

Growth is bounded the way the reference truncates below the stable mark
(TransactionLog.cs): once every participant has acknowledged a decision
and a retention window has passed, the TM calls ``rewrite`` with the
records still live; a ``seq`` watermark record preserves the shard's
version sequence across compactions.

Blocking I/O (fsync, sqlite) runs via ``loop.run_in_executor`` so a
commit decision never stalls the silo's event loop.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import os
import sqlite3
import threading
from typing import Iterable

try:
    import fcntl
except ImportError:  # pragma: no cover — non-POSIX fallback (tests/dev)
    fcntl = None

__all__ = ["TransactionLog", "InMemoryTransactionLog", "FileTransactionLog",
           "SqliteTransactionLog"]

# decision value reserved for the compaction watermark record
_SEQ_MARK = "__seq__"


class TransactionLog:
    """Provider contract. One log instance may be shared by several TM
    shards; records carry the shard id so each shard replays its own."""

    async def append(self, shard: int, txn: str, decision: str,
                     version: int) -> None:
        raise NotImplementedError

    async def decide(self, shard: int, txn: str, decision: str,
                     version: int) -> tuple[str, int]:
        """Atomic first-decision-wins append: if the log already holds a
        decision for ``txn`` (e.g. logged by a concurrent duplicate TM
        incarnation during a membership transition), return THAT record
        without writing; otherwise append and return the proposal. This
        is what makes presumed abort safe against a racing commit — the
        log, not any single activation's memory, is the serialization
        point (TransactionLog.cs as the TM's durable truth)."""
        raise NotImplementedError

    async def replay(self, shard: int) -> tuple[int, dict[str, tuple[str, int]]]:
        """Return (max_version_seen, {txn: (decision, version)}) for one
        shard."""
        raise NotImplementedError

    async def rewrite(self, shard: int,
                      live: dict[str, tuple[str, int]], seq: int) -> None:
        """Compact: replace the shard's records with ``live`` plus a seq
        watermark. Other shards' records are preserved."""
        raise NotImplementedError


class InMemoryTransactionLog(TransactionLog):
    """Test/dev backend; survives silo restarts when the instance is shared
    (the InMemoryTransactionLog analog of InMemoryMembershipTable)."""

    def __init__(self) -> None:
        self.records: list[tuple[int, str, str, int]] = []
        self._index: dict[tuple[int, str], tuple[str, int]] = {}

    async def append(self, shard: int, txn: str, decision: str,
                     version: int) -> None:
        self.records.append((shard, txn, decision, version))
        self._index.setdefault((shard, txn), (decision, version))

    async def decide(self, shard: int, txn: str, decision: str,
                     version: int) -> tuple[str, int]:
        prior = self._index.get((shard, txn))
        if prior is not None:
            return prior
        await self.append(shard, txn, decision, version)
        return (decision, version)

    async def replay(self, shard: int) -> tuple[int, dict[str, tuple[str, int]]]:
        return _fold(r for r in self.records if r[0] == shard)

    async def rewrite(self, shard: int,
                      live: dict[str, tuple[str, int]], seq: int) -> None:
        self.records = [r for r in self.records if r[0] != shard]
        self.records.append((shard, "", _SEQ_MARK, seq))
        self.records.extend((shard, t, d, v) for t, (d, v) in live.items())
        self._index = {k: v for k, v in self._index.items()
                       if k[0] != shard}
        self._index.update({(shard, t): d for t, d in live.items()})


class FileTransactionLog(TransactionLog):
    """Append-only JSONL file, fsync'd per decision — the durability
    point of the 2PC (TransactionLog.cs's storage append). The fsync runs
    in the default executor; a lock serializes writers so compaction's
    replace-rename cannot race an append."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._io_lock = threading.Lock()
        # decide() index: (shard, txn) → FIRST record. Built from the
        # file and kept current for this process's writes; cross-process
        # writers are detected by file growth and serialized by an OS
        # file lock (the threading lock only covers this process).
        self._index: dict[tuple[int, str], tuple[str, int]] | None = None
        self._scanned_size = -1

    @contextlib.contextmanager
    def _os_lock(self):
        """Cross-process exclusive lock (fcntl.flock on a sidecar): the
        first-decision-wins guarantee must hold between silo PROCESSES
        sharing the file, not just between tasks of one process."""
        if fcntl is None:
            yield
            return
        with open(self.path + ".lock", "a+") as lk:
            fcntl.flock(lk.fileno(), fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(lk.fileno(), fcntl.LOCK_UN)

    def _write_locked(self, shard: int, txn: str, decision: str,
                      version: int) -> None:
        line = json.dumps({"s": shard, "t": txn, "d": decision,
                           "v": version}, separators=(",", ":"))
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())
            size = f.tell()
        if self._index is not None:
            self._index.setdefault((shard, txn), (decision, version))
            self._scanned_size = size

    def _refresh_index_locked(self) -> dict:
        """(Re)build the index iff the file changed since the last scan —
        the common decide() for a fresh txn costs one getsize(), not a
        full-file parse."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            size = 0
        if self._index is None or size != self._scanned_size:
            idx: dict = {}
            for s, t, d, v in self._read_all():
                if d != _SEQ_MARK:
                    idx.setdefault((s, t), (d, v))  # first decision wins
            self._index = idx
            self._scanned_size = size
        return self._index

    async def append(self, shard: int, txn: str, decision: str,
                     version: int) -> None:
        def write() -> None:
            with self._io_lock, self._os_lock():
                self._write_locked(shard, txn, decision, version)

        await asyncio.get_running_loop().run_in_executor(None, write)

    async def decide(self, shard: int, txn: str, decision: str,
                     version: int) -> tuple[str, int]:
        def decide_locked() -> tuple[str, int]:
            with self._io_lock, self._os_lock():
                prior = self._refresh_index_locked().get((shard, txn))
                if prior is not None:
                    return prior
                self._write_locked(shard, txn, decision, version)
                return (decision, version)

        return await asyncio.get_running_loop().run_in_executor(
            None, decide_locked)

    def _read_all(self) -> list[tuple[int, str, str, int]]:
        """Callers must hold ``_io_lock`` — an unlocked read can observe
        a torn half-flushed line from a concurrent append/rewrite."""
        if not os.path.exists(self.path):
            return []
        out = []
        with open(self.path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                r = json.loads(line)
                out.append((r["s"], r["t"], r["d"], r["v"]))
        return out

    async def replay(self, shard: int) -> tuple[int, dict[str, tuple[str, int]]]:
        def read():
            with self._io_lock:
                return self._read_all()

        rows = await asyncio.get_running_loop().run_in_executor(None, read)
        return _fold(r for r in rows if r[0] == shard)

    async def rewrite(self, shard: int,
                      live: dict[str, tuple[str, int]], seq: int) -> None:
        def compact() -> None:
            with self._io_lock:  # _read_all is called under the lock here
                keep = [r for r in self._read_all() if r[0] != shard]
                keep.append((shard, "", _SEQ_MARK, seq))
                keep.extend((shard, t, d, v) for t, (d, v) in live.items())
                tmp = self.path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    for s, t, d, v in keep:
                        f.write(json.dumps(
                            {"s": s, "t": t, "d": d, "v": v},
                            separators=(",", ":")) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)
                self._index = None  # rebuilt lazily from the new file
                self._scanned_size = -1

        await asyncio.get_running_loop().run_in_executor(None, compact)


class SqliteTransactionLog(TransactionLog):
    """Sqlite-backed log (the AdoNet analog). One connection, WAL mode,
    used from the executor; ``close()`` releases it."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db_lock = threading.Lock()
        with self._db_lock:
            self._db.execute("PRAGMA journal_mode=WAL")
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS txn_log ("
                " shard INTEGER, txn TEXT, decision TEXT, version INTEGER)")
            # migration: pre-index databases may hold duplicate (shard,
            # txn) rows from the plain-INSERT era — keep the FIRST record
            # per key (first-decision-wins) or the index creation fails
            self._db.execute(
                "DELETE FROM txn_log WHERE rowid NOT IN"
                " (SELECT MIN(rowid) FROM txn_log GROUP BY shard, txn)")
            # first-decision-wins is enforced by the database itself
            # (decide() uses INSERT OR IGNORE against this index)
            self._db.execute(
                "CREATE UNIQUE INDEX IF NOT EXISTS txn_log_pk"
                " ON txn_log(shard, txn)")
            self._db.commit()

    def close(self) -> None:
        with self._db_lock:
            self._db.close()

    async def append(self, shard: int, txn: str, decision: str,
                     version: int) -> None:
        def write() -> None:
            with self._db_lock:
                self._db.execute(
                    "INSERT OR IGNORE INTO txn_log VALUES (?,?,?,?)",
                    (shard, txn, decision, version))
                self._db.commit()

        await asyncio.get_running_loop().run_in_executor(None, write)

    async def decide(self, shard: int, txn: str, decision: str,
                     version: int) -> tuple[str, int]:
        def decide_tx() -> tuple[str, int]:
            with self._db_lock:
                self._db.execute(
                    "INSERT OR IGNORE INTO txn_log VALUES (?,?,?,?)",
                    (shard, txn, decision, version))
                self._db.commit()
                row = self._db.execute(
                    "SELECT decision, version FROM txn_log"
                    " WHERE shard=? AND txn=?", (shard, txn)).fetchone()
            return (row[0], row[1])

        return await asyncio.get_running_loop().run_in_executor(
            None, decide_tx)

    async def replay(self, shard: int) -> tuple[int, dict[str, tuple[str, int]]]:
        def read():
            with self._db_lock:
                return self._db.execute(
                    "SELECT shard, txn, decision, version FROM txn_log"
                    " WHERE shard=?", (shard,)).fetchall()

        return _fold(await asyncio.get_running_loop().run_in_executor(
            None, read))

    async def rewrite(self, shard: int,
                      live: dict[str, tuple[str, int]], seq: int) -> None:
        def compact() -> None:
            with self._db_lock:
                self._db.execute("DELETE FROM txn_log WHERE shard=?",
                                 (shard,))
                self._db.execute(
                    "INSERT OR IGNORE INTO txn_log VALUES (?,?,?,?)",
                    (shard, "", _SEQ_MARK, seq))
                self._db.executemany(
                    "INSERT OR IGNORE INTO txn_log VALUES (?,?,?,?)",
                    [(shard, t, d, v) for t, (d, v) in live.items()])
                self._db.commit()

        await asyncio.get_running_loop().run_in_executor(None, compact)


def _fold(rows: Iterable[tuple[int, str, str, int]]
          ) -> tuple[int, dict[str, tuple[str, int]]]:
    seq = 0
    decisions: dict[str, tuple[str, int]] = {}
    for _, txn, decision, version in rows:
        if decision == _SEQ_MARK:
            seq = max(seq, version)
            continue
        # FIRST decision wins: decide() guarantees one record per txn,
        # but a legacy log (or a lost cross-process race on a filesystem
        # without flock) may hold duplicates — replay must agree with
        # decide()'s winner, not invert it
        decisions.setdefault(txn, (decision, version))
        seq = max(seq, version)
    return seq, decisions
