"""Durable transaction commit log.

Re-design of /root/reference/src/Orleans.Transactions/TransactionLog.cs
(storage-backed commit log the TM appends decisions to before announcing
them) behind a pluggable provider interface, with in-memory / append-only
file / sqlite backends — the same provider split the membership table and
reminder table use (cloud log storage such as
Orleans.Transactions.AzureStorage maps to the File/Sqlite backends here;
no cloud egress in scope).

The log is the TM's durable truth: a decision is COMMITTED the moment its
record is appended, before any participant hears the outcome. A TM
activation replays the log on activate (seq + decision map), which is what
makes TM failover safe: in-doubt participants query ``decision_of`` against
the recovered map.
"""

from __future__ import annotations

import json
import os
import sqlite3
from typing import Iterable

__all__ = ["TransactionLog", "InMemoryTransactionLog", "FileTransactionLog",
           "SqliteTransactionLog"]


class TransactionLog:
    """Provider contract. One log instance may be shared by several TM
    shards; records carry the shard id so each shard replays its own."""

    async def append(self, shard: int, txn: str, decision: str,
                     version: int) -> None:
        raise NotImplementedError

    async def replay(self, shard: int) -> tuple[int, dict[str, str]]:
        """Return (max_version_seen, {txn: decision}) for one shard."""
        raise NotImplementedError


class InMemoryTransactionLog(TransactionLog):
    """Test/dev backend; survives silo restarts when the instance is shared
    (the InMemoryTransactionLog analog of InMemoryMembershipTable)."""

    def __init__(self) -> None:
        self.records: list[tuple[int, str, str, int]] = []

    async def append(self, shard: int, txn: str, decision: str,
                     version: int) -> None:
        self.records.append((shard, txn, decision, version))

    async def replay(self, shard: int) -> tuple[int, dict[str, str]]:
        return _fold(r for r in self.records if r[0] == shard)


class FileTransactionLog(TransactionLog):
    """Append-only JSONL file, fsync'd per decision — the durability
    point of the 2PC (TransactionLog.cs's storage append)."""

    def __init__(self, path: str) -> None:
        self.path = path

    async def append(self, shard: int, txn: str, decision: str,
                     version: int) -> None:
        line = json.dumps({"s": shard, "t": txn, "d": decision,
                           "v": version}, separators=(",", ":"))
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())

    async def replay(self, shard: int) -> tuple[int, dict[str, str]]:
        if not os.path.exists(self.path):
            return 0, {}

        def rows():
            with open(self.path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    r = json.loads(line)
                    if r["s"] == shard:
                        yield r["s"], r["t"], r["d"], r["v"]

        return _fold(rows())


class SqliteTransactionLog(TransactionLog):
    """Sqlite-backed log (the AdoNet analog)."""

    def __init__(self, path: str) -> None:
        self.path = path
        with self._db() as db:
            db.execute(
                "CREATE TABLE IF NOT EXISTS txn_log ("
                " shard INTEGER, txn TEXT, decision TEXT, version INTEGER)")

    def _db(self) -> sqlite3.Connection:
        return sqlite3.connect(self.path)

    async def append(self, shard: int, txn: str, decision: str,
                     version: int) -> None:
        with self._db() as db:
            db.execute("INSERT INTO txn_log VALUES (?,?,?,?)",
                       (shard, txn, decision, version))

    async def replay(self, shard: int) -> tuple[int, dict[str, str]]:
        with self._db() as db:
            rows = db.execute(
                "SELECT shard, txn, decision, version FROM txn_log"
                " WHERE shard=?", (shard,)).fetchall()
        return _fold(rows)


def _fold(rows: Iterable[tuple[int, str, str, int]]
          ) -> tuple[int, dict[str, str]]:
    seq = 0
    decisions: dict[str, str] = {}
    for _, txn, decision, version in rows:
        decisions[txn] = decision
        seq = max(seq, version)
    return seq, decisions
