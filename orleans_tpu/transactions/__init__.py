"""ACID multi-grain transactions (reference L11, src/Orleans.Transactions/ +
src/Orleans.Runtime/Transactions/): @transactional scopes, TransactionalState
versioned grain state, singleton TM grain running 2PC."""

from .context import ambient_txn
from .manager import (
    TransactionAgent,
    TransactionManagerGrain,
    add_transactions,
    transactional,
)
from .state import TransactionalGrain, TransactionalState

__all__ = [
    "transactional", "add_transactions", "ambient_txn",
    "TransactionAgent", "TransactionManagerGrain",
    "TransactionalGrain", "TransactionalState",
]
