"""ACID multi-grain transactions (reference L11, src/Orleans.Transactions/ +
src/Orleans.Runtime/Transactions/): @transactional scopes, TransactionalState
versioned grain state, singleton TM grain running 2PC."""

from .context import TransactionInfo, ambient_txn
from .log import (
    FileTransactionLog,
    InMemoryTransactionLog,
    SqliteTransactionLog,
    TransactionLog,
)
from .manager import (
    TransactionAgent,
    TransactionManagerGrain,
    add_transactions,
    transactional,
)
from .state import TransactionalGrain, TransactionalState

__all__ = [
    "transactional", "add_transactions", "ambient_txn", "TransactionInfo",
    "TransactionAgent", "TransactionManagerGrain",
    "TransactionalGrain", "TransactionalState",
    "TransactionLog", "InMemoryTransactionLog", "FileTransactionLog",
    "SqliteTransactionLog",
]
