"""Transaction ambient context: the txn id rides RequestContext so it flows
through nested grain calls exactly like the reference's TransactionInfo
message header (Message headers transaction info; scope opened in
InsideRuntimeClient.Invoke, /root/reference/src/Orleans.Runtime/Core/
InsideRuntimeClient.cs:313-438)."""

from __future__ import annotations

from ..runtime.context import RequestContext

TXN_KEY = "orleans.txn.id"

__all__ = ["TXN_KEY", "ambient_txn", "set_ambient_txn", "clear_ambient_txn"]


def ambient_txn() -> str | None:
    return RequestContext.get(TXN_KEY)


def set_ambient_txn(txn_id: str) -> None:
    RequestContext.set(TXN_KEY, txn_id)


def clear_ambient_txn() -> None:
    RequestContext.remove(TXN_KEY)
