"""Transaction ambient context: a TransactionInfo rides RequestContext so
it flows through nested grain calls exactly like the reference's
TransactionInfo message header (scope opened in InsideRuntimeClient.Invoke,
/root/reference/src/Orleans.Runtime/Core/InsideRuntimeClient.cs:313-438).

Participants are collected CALLER-SIDE as the call tree runs (each
TransactionalState first-touch registers its grain into the ambient info;
callee-side joins ride back to the caller on the response's
``transaction_info`` header) — so starting a transaction and joining it
cost zero TM round trips; the TM hears about the transaction exactly once,
at commit, with the full participant set. This is the reference's own
evolution of the design (the 2.0-preview per-call TM chatter was replaced
by agent-side collection), and it is what makes the TM a sequencer rather
than a bottleneck.
"""

from __future__ import annotations

import itertools
import random
import time
from typing import TYPE_CHECKING

from ..runtime.context import TXN_KEY, RequestContext

if TYPE_CHECKING:
    from ..core.ids import GrainId

__all__ = ["TXN_KEY", "TransactionInfo", "ambient_txn", "set_ambient_txn",
           "clear_ambient_txn"]

# txn ids: random 8-hex head (spreads txns over TM shards) + process tag +
# counter (uniqueness) — ~20× cheaper than uuid4 on the commit hot path
_proc_tag = f"{random.getrandbits(48):012x}"
_txn_counter = itertools.count(1)


class TransactionInfo:
    """One transaction's identity + collected participant set."""

    __slots__ = ("id", "deadline", "participants", "ts")

    def __init__(self, id: str | None = None,
                 deadline: float | None = None,
                 participants: dict | None = None,
                 ts: tuple | None = None):
        self.id = id or (f"{random.getrandbits(32):08x}"
                         f"{_proc_tag}{next(_txn_counter):x}")
        self.deadline = deadline if deadline is not None else \
            time.time() + 10.0
        # wound-wait priority timestamp: totally ordered cluster-wide
        # (wall clock, then process tag, then sequence breaks ties).
        # Conflict retries REUSE the original ts (manager.transactional)
        # so a repeatedly-dying transaction ages into the oldest — and
        # therefore winning — one: livelock-free by construction.
        self.ts: tuple = ts if ts is not None else \
            (time.time(), _proc_tag, next(_txn_counter))
        # str(grain_id) -> (GrainId, interface_name)
        self.participants: dict[str, tuple["GrainId", str]] = \
            participants or {}

    def join(self, grain_id: "GrainId", iface: str) -> None:
        self.participants[str(grain_id)] = (grain_id, iface)

    def merge(self, participants: dict) -> None:
        """Fold a callee's joins (piggybacked on its response) into the
        caller's set — idempotent, so the in-proc shared-object case and
        the cross-process serialized case behave identically."""
        self.participants.update(participants)

    # pickled into response headers for the cross-process merge
    def __reduce__(self):
        return (TransactionInfo, (self.id, self.deadline,
                                  dict(self.participants), self.ts))

    def __repr__(self) -> str:
        return (f"TransactionInfo({self.id[:8]}, "
                f"{len(self.participants)} participants)")


def ambient_txn() -> TransactionInfo | None:
    return RequestContext.get(TXN_KEY)


def set_ambient_txn(info: TransactionInfo) -> None:
    RequestContext.set(TXN_KEY, info)


def clear_ambient_txn() -> None:
    RequestContext.remove(TXN_KEY)
