"""Transactional grain state: versioned values with 2PC participation.

Re-design of /root/reference/src/Orleans.Transactions/State/
TransactionalState.cs:611 (ITransactionalState<T> — versioned copies per
transaction, read-version validation, prepare/commit/abort participation)
plus the grain-facing facet. The reference validates at a central TM with
version ranges; here validation is pushed to the participant (optimistic
read-version check + short prepare lock), with the TM (manager.py) running
the 2PC rounds — same outcome: serializable multi-grain transactions.

Usage::

    class AccountGrain(TransactionalGrain):
        def __init__(self):
            super().__init__()
            self.balance = TransactionalState("balance", default=0)

        @transactional
        async def deposit(self, amount):
            v = await self.balance.get()
            await self.balance.set(v + amount)
"""

from __future__ import annotations

import asyncio
import time
from typing import Any

from ..core.errors import TransactionAbortedError, TransactionConflictError
from ..core.serialization import deep_copy
from ..runtime.grain import Grain, always_interleave
from .context import ambient_txn

__all__ = ["TransactionalState", "TransactionalGrain"]

PREPARE_LOCK_TTL = 10.0  # steal an expired lock: TM died mid-2PC
# how long a non-transactional read keeps retrying resolution of an
# in-doubt prepared write (TM failover window) before serving the last
# committed value
IN_DOUBT_READ_TIMEOUT = 5.0
# a prepare lock held this long is queried against the TM's decision log
# from the entry wait loop (the outcome may be logged but undelivered);
# past IN_DOUBT_FORCE_AFTER the query forces a durable presumed-abort for
# an unknown txn — without this, every waiter sits out the full
# PREPARE_LOCK_TTL after a TM dies mid-2PC, and those 10s stalls are long
# enough to false-kill healthy silos via missed liveness probes
IN_DOUBT_QUERY_AFTER = 0.25
IN_DOUBT_FORCE_AFTER = 1.0
# A workspace blocks other transactions' entry (wound-wait) only this long
# after first touch. Entry blocking is a conflict-avoidance optimization —
# the read-version check at prepare is what guarantees serializability —
# so a root that died without aborting (silo kill) stalls waiters for at
# most this window instead of its full transaction deadline.
INTENT_TTL = 1.0

# Wound registry (wound-wait deadlock avoidance): an OLDER transaction
# arriving at a state held by a YOUNGER one marks the younger txn wounded;
# the wounded txn aborts at its next entry/prepare checkpoint and retries
# at the root with its original priority. Silo-local by design — a wound
# that fails to reach a remote participant merely downgrades that conflict
# to the optimistic read-version abort at prepare (safety is never the
# wound's job). Entries are pruned by age; retries use fresh txn ids, so
# stale wounds can never hit a live transaction.
_wounded: dict[str, float] = {}
_WOUND_TTL = 5.0


def _prune_wounds(now: float) -> None:
    if len(_wounded) > 256:
        for tid, t in list(_wounded.items()):
            if now - t > _WOUND_TTL:
                _wounded.pop(tid, None)


class TransactionalState:
    """One versioned value owned by a grain."""

    def __init__(self, name: str, default: Any = None,
                 storage_name: str = "Default"):
        self.name = name
        self.default = default
        self.storage_name = storage_name
        self.committed: Any = default
        self.committed_version: int = 0
        self.owner: "TransactionalGrain | None" = None
        # txn id -> {"value", "read_version", "written"}
        self.workspace: dict[str, dict] = {}
        self.lock: tuple[str, float] | None = None  # (txn id, deadline)
        self._etag: str | None = None  # storage etag of the committed row
        # durably-prepared write awaiting its outcome: {"txn", "value",
        # "read_version", "written": True}. Persisted at prepare time so a
        # participant crash between prepare and commit cannot lose a write
        # the TM logged as committed (the prepare-record half of
        # TransactionalState.cs's persistence protocol).
        self.pending_prepare: dict | None = None
        self._prep_etag: str | None = None
        self._release_event: asyncio.Event | None = None

    # -- grain-facing API (PerformRead/PerformUpdate) -------------------
    async def get(self) -> Any:
        info = ambient_txn()
        if info is None:
            if self.pending_prepare is not None and self.owner is not None:
                # An in-doubt prepared write is outstanding: the value a
                # logged commit may be about to replace must not be
                # served. One resolution attempt is not enough right
                # after a failover — the TM shard may still be
                # reactivating (its first decision_of can fail on stale
                # directory routes) — so retry briefly; the loop ends the
                # moment the decision applies (or the prepare is dropped
                # as aborted). After sustained TM unreachability we fall
                # through to the committed value: availability over
                # blocking forever, and the prepare stays held for the
                # next reader/prepare to resolve.
                deadline = time.time() + IN_DOUBT_READ_TIMEOUT
                while self.pending_prepare is not None:
                    await self.owner._resolve_in_doubt(time.time(),
                                                       force_query=True)
                    if self.pending_prepare is None or \
                            time.time() >= deadline:
                        break
                    await asyncio.sleep(0.05)
            return deep_copy(self.committed)
        ws = await self._enter(info)
        return ws["value"]

    async def set(self, value: Any) -> None:
        info = ambient_txn()
        if info is None:
            raise TransactionAbortedError(
                f"state {self.name!r} can only be written inside a "
                "transaction (wrap the method with @transactional)")
        ws = await self._enter(info)
        ws["value"] = value
        ws["written"] = True

    def _signal_release(self) -> None:
        ev = self._release_event
        if ev is not None:
            ev.set()

    def _entry_blocked(self, info, now: float) -> bool:
        """Wound-wait entry gate. Returns True while ``info`` must wait:
        a fresh prepare lock (mid-2PC, settles within a round) or another
        transaction's live workspace blocks entry. An OLDER arrival wounds
        every younger holder on its way into the wait — the wounded txn
        aborts at its next checkpoint and retries — so every wait edge
        that survives points young→old and cycles are impossible.
        Workspaces past their deadline are abandoned debris (crashed or
        timed-out root) and are swept; intents older than INTENT_TTL stop
        blocking (dead-root bound) — the read-version check at prepare
        remains the safety net for both relaxations."""
        if self.lock is not None and self.lock[0] != info.id and \
                self.lock[1] > now:
            return True
        blocked = False
        for oid, ows in list(self.workspace.items()):
            if oid == info.id:
                continue
            if ows["deadline"] <= now:
                # past its deadline: wound rather than delete. Deleting
                # would let a prepare that races the deadline see "no
                # workspace → vote True" and commit with this write
                # silently dropped; wounding forces its prepare to vote
                # False. The workspace itself is only reaped well past
                # the deadline (TM deadline checks make a commit
                # impossible by then).
                _wounded.setdefault(oid, now)
                if ows["deadline"] <= now - _WOUND_TTL:
                    self.workspace.pop(oid, None)
                continue
            if now - ows["entered"] >= INTENT_TTL:
                continue  # stale intent (dead root?): enter optimistically
            if oid in _wounded:
                continue  # dying txn: never wait on it (it cannot commit)
            if info.ts < ows["ts"]:
                # older wounds younger holder AND proceeds immediately —
                # the wounded txn can no longer pass prepare(), so entering
                # alongside its doomed workspace is safe (read-version
                # validation is the formal guarantee) and keeps the wound's
                # ≤poll-interval discovery latency off OUR critical path
                _wounded.setdefault(oid, now)
                continue
            blocked = True
        return blocked

    async def _enter(self, info) -> dict:
        ws = self.workspace.get(info.id)
        if ws is None:
            # Pessimistic entry with wound-wait deadlock avoidance (the
            # lock-queue role of the reference's TransactionalState,
            # State/TransactionalState.cs:611): one transaction owns a
            # state's workspace at a time; requesters WAIT for release
            # (young waiting for old is always safe; old waiting for
            # young first wounds it, see _entry_blocked), and wounded
            # transactions abort at this checkpoint to retry at the root
            # with their original priority (context.TransactionInfo.ts) —
            # the oldest transaction is never wounded and never waits on
            # a cycle, so the system always makes progress. (Round 2's
            # optimistic entry measured ~6.5 attempts per commit at
            # concurrency 32 on the contended bank workload; pessimistic
            # entry converts those doomed 2PC rounds into short waits.)
            while True:
                now = time.time()
                if info.id in _wounded:
                    raise TransactionConflictError(
                        f"transaction {info.id} wounded by an older "
                        f"transaction at state {self.name!r}")
                if not self._entry_blocked(info, now):
                    break
                if now >= info.deadline:
                    raise TransactionConflictError(
                        f"transaction {info.id} deadline passed waiting "
                        f"for state {self.name!r}")
                if self.lock is not None and self.lock[0] != info.id and \
                        self.pending_prepare is not None and \
                        self.owner is not None:
                    # blocked on a mid-2PC prepare: the decision may be
                    # logged but undelivered (TM died / slow fan-out) —
                    # resolve through the decision log instead of sitting
                    # out the lock TTL
                    lock_age = PREPARE_LOCK_TTL - (self.lock[1] - now)
                    if lock_age > IN_DOUBT_QUERY_AFTER:
                        await self.owner._resolve_in_doubt(
                            now, force_query=True,
                            resolve_fresh=lock_age > IN_DOUBT_FORCE_AFTER)
                        if not self._entry_blocked(info, time.time()) \
                                and info.id not in _wounded:
                            break  # settled: enter now
                        # else fall through to the paced wait
                ev = self._release_event
                if ev is None or ev.is_set():
                    ev = self._release_event = asyncio.Event()
                try:
                    await asyncio.wait_for(
                        ev.wait(), min(0.05, info.deadline - now))
                except asyncio.TimeoutError:
                    pass  # re-check: TTL expiry / debris sweep
            self.owner._txn_join(info)
            ws = self.workspace[info.id] = {
                "value": deep_copy(self.committed),
                "read_version": self.committed_version,
                "written": False,
                "ts": info.ts,
                "deadline": info.deadline,
                "entered": time.time(),
            }
        return ws

    # -- 2PC participation ----------------------------------------------
    def prepare(self, txn: str, now: float) -> bool:
        ws = self.workspace.get(txn)
        if ws is None:
            return True  # joined via another state of the same grain
        if txn in _wounded:
            return False  # wounded by an older transaction: give way
        if self.lock is not None and self.lock[1] > now and \
                self.lock[0] != txn:
            return False  # another transaction is mid-commit on this state
        if self.pending_prepare is not None and \
                self.pending_prepare["txn"] != txn:
            # an in-doubt durable prepare survived resolution (TM
            # unreachable): its write may still commit — refuse to
            # validate over it even though the lock expired
            return False
        if ws["read_version"] != self.committed_version:
            return False  # someone committed since we read
        self.lock = (txn, now + PREPARE_LOCK_TTL)
        return True

    def commit(self, txn: str, commit_version: int) -> bool:
        """Apply; returns True when the value changed (needs persist)."""
        ws = self.workspace.pop(txn, None)
        if self.lock is not None and self.lock[0] == txn:
            self.lock = None
        if self.pending_prepare is not None and \
                self.pending_prepare["txn"] == txn:
            if ws is None:
                # crash-recovered prepare: the in-memory workspace died
                # with the previous activation, but the durable prepare
                # record carries the write
                ws = self.pending_prepare
            self.pending_prepare = None
        self._signal_release()
        if ws is None or not ws["written"]:
            return False
        self.committed = ws["value"]
        self.committed_version = commit_version
        return True

    def abort(self, txn: str) -> None:
        self.workspace.pop(txn, None)
        now = time.time()
        _wounded.pop(txn, None)
        _prune_wounds(now)
        if self.lock is not None and self.lock[0] == txn:
            self.lock = None
        if self.pending_prepare is not None and \
                self.pending_prepare["txn"] == txn:
            self.pending_prepare = None
        self._signal_release()


class TransactionalGrain(Grain):
    """Base for grains holding TransactionalState: wires state discovery,
    persistence, and the 2PC surface the TM calls (the participant half of
    TransactionAgent.cs:98)."""

    @property
    def _txn_joined(self) -> set[str]:
        # lazy so subclasses need not call super().__init__()
        return self.__dict__.setdefault("_txn_joined_set", set())

    def _txn_states(self) -> list[TransactionalState]:
        out = []
        for v in vars(self).values():
            if isinstance(v, TransactionalState):
                if v.owner is None:
                    v.owner = self
                out.append(v)
        return out

    # -- lifecycle: recover committed values + in-doubt prepares ---------
    async def on_activate(self) -> None:
        silo = self._activation.runtime
        now = time.time()
        for st in self._txn_states():
            provider = silo.storage_manager.get(st.storage_name)
            if provider is None:
                continue
            data, etag = await provider.read(
                self._txn_storage_type(st), self.grain_id)
            st._etag = etag
            if data is not None:
                st.committed = data["value"]
                st.committed_version = data["version"]
            prep, petag = await provider.read(
                self._txn_prep_type(st), self.grain_id)
            st._prep_etag = petag
            if prep is not None and \
                    prep["read_version"] >= st.committed_version:
                # the previous activation died between prepare and
                # outcome: hold the prepare (locked) and ask the TM
                # (a prepare whose read_version is already stale lost
                # its transaction — the commit round would have bumped
                # committed_version past it — so it is droppable)
                st.pending_prepare = prep
                st.lock = (prep["txn"], now + PREPARE_LOCK_TTL)
        await self._resolve_in_doubt(now, force_query=True)

    def _txn_storage_type(self, st: TransactionalState) -> str:
        return f"txn:{type(self).__name__}:{st.name}"

    def _txn_prep_type(self, st: TransactionalState) -> str:
        return f"txnprep:{type(self).__name__}:{st.name}"

    async def _resolve_in_doubt(self, now: float,
                                force_query: bool = False,
                                resolve_fresh: bool = False) -> None:
        """Resolve held prepares whose outcome never arrived by asking
        the transaction's TM shard (``decision_of`` against the durable
        decision log) — committed → apply the prepared write; aborted →
        drop it; unknown after the lock expired → presumed abort (the TM
        logs before announcing, so an unknown txn can never later commit
        without a fresh prepare round). ``force_query=True`` (reactivation)
        queries even while the lock is fresh, so a decision the previous
        incarnation missed applies immediately; an unknown outcome is then
        held until expiry in case the 2PC is still in flight.
        ``resolve_fresh=True`` escalates: an unknown txn is durably
        presumed-aborted even while the lock is fresh — used by the entry
        wait loop once a lock has been in-doubt past IN_DOUBT_FORCE_AFTER
        (first-decision-wins at the log makes this safe: a late commit
        attempt for that txn finds the abort already decided)."""
        silo = self._activation.runtime
        agent = getattr(silo, "transactions", None)
        for st in self._txn_states():
            pending = st.pending_prepare
            if pending is None:
                continue
            expired = (st.lock is None or st.lock[1] <= now
                       or st.lock[0] != pending["txn"])
            if not expired and not force_query:
                continue                  # outcome may still be in flight
            decision = None
            reachable = False
            if agent is not None:
                try:
                    # resolve=True on expiry: the TM logs a durable
                    # presumed-abort for an unknown txn, so a slow 2PC
                    # can no longer commit after we drop the prepare
                    decision = await agent.decision_of(
                        pending["txn"], resolve=expired or resolve_fresh)
                    reachable = True
                except Exception:  # noqa: BLE001 — TM unreachable: leave
                    # the prepare held; the next prepare/retry re-asks
                    continue
            if decision is not None and decision[0] == "committed":
                if st.commit(pending["txn"], decision[1]):
                    await self._persist_committed(st, silo)
                await self._clear_prepare(st, silo)
            elif decision is not None:
                st.abort(pending["txn"])
                await self._clear_prepare(st, silo)
            elif reachable and (expired or resolve_fresh):
                # the authoritative shard has no record: presumed abort
                st.abort(pending["txn"])
                await self._clear_prepare(st, silo)
            # else: unknown but lock still fresh — hold for the outcome

    async def _persist_committed(self, st: TransactionalState, silo) -> None:
        provider = silo.storage_manager.get(st.storage_name)
        if provider is not None:
            st._etag = await provider.write(
                self._txn_storage_type(st), self.grain_id,
                {"value": st.committed, "version": st.committed_version},
                etag=st._etag)

    async def _persist_prepare(self, st: TransactionalState, silo,
                               prep: dict) -> None:
        provider = silo.storage_manager.get(st.storage_name)
        if provider is not None:
            st._prep_etag = await provider.write(
                self._txn_prep_type(st), self.grain_id, prep,
                etag=st._prep_etag)

    async def _clear_prepare(self, st: TransactionalState, silo) -> None:
        provider = silo.storage_manager.get(st.storage_name)
        if provider is not None and st._prep_etag is not None:
            await provider.clear(self._txn_prep_type(st), self.grain_id,
                                 st._prep_etag)
            st._prep_etag = None

    # -- join: register into the ambient participant set (caller-side
    # collection — zero TM round trips; the set rides back to the root
    # on response headers, see transactions/context.py) ------------------
    def _txn_join(self, info) -> None:
        if info.id in self._txn_joined:
            return
        self._txn_joined.add(info.id)
        info.join(self.grain_id, type(self).__name__)

    # -- 2PC surface called by the TM (interleave: the root caller is
    # blocked awaiting commit while these arrive) ------------------------
    @always_interleave
    async def _txn_prepare(self, txn: str) -> bool:
        now = time.time()
        if txn not in self._txn_joined:
            # No trace of this transaction on this activation. The
            # per-state "ws is None → vote True" below is for multi-state
            # grains where the txn touched a sibling state — but a
            # participant that CRASHED after entering its workspace
            # reactivates with no workspace at all, and voting True here
            # lets the TM commit a transfer whose write evaporated with
            # the old activation (measured: one unmatched transfer leg
            # per ~10 kill runs before this guard). No join trace → the
            # write is gone → the transaction must abort and retry.
            return False
        states = self._txn_states()
        if any(st.pending_prepare is not None
               and (st.lock is None or st.lock[1] <= now
                    or st.lock[0] != st.pending_prepare["txn"])
               for st in states):
            # an earlier transaction's outcome never arrived and its lock
            # expired: resolve it via the TM's durable decision before
            # voting — stealing the lock blind would let this transaction
            # validate against a read_version the in-doubt commit is
            # about to bump (the divergence the decision log exists to
            # prevent)
            await self._resolve_in_doubt(now)
        votes = [st.prepare(txn, now) for st in states]
        if not all(votes):
            for st in states:
                st.abort(txn)
            self._txn_joined.discard(txn)
            return False
        silo = self._activation.runtime
        try:
            for st in states:
                ws = st.workspace.get(txn)
                if ws is not None and ws["written"]:
                    prep = {"txn": txn, "value": ws["value"],
                            "read_version": ws["read_version"],
                            "written": True}
                    st.pending_prepare = prep
                    await self._persist_prepare(st, silo, prep)
        except Exception:  # noqa: BLE001 — durable prepare failed: vote no
            for st in states:
                st.abort(txn)
            self._txn_joined.discard(txn)
            return False
        return True

    @always_interleave
    async def _txn_commit(self, txn: str, commit_version: int) -> None:
        silo = self._activation.runtime
        for st in self._txn_states():
            had_prepare = st.pending_prepare is not None and \
                st.pending_prepare["txn"] == txn
            if st.commit(txn, commit_version):
                await self._persist_committed(st, silo)
            if had_prepare:
                await self._clear_prepare(st, silo)
        self._txn_joined.discard(txn)

    @always_interleave
    async def _txn_abort(self, txn: str) -> None:
        silo = self._activation.runtime
        for st in self._txn_states():
            had_prepare = st.pending_prepare is not None and \
                st.pending_prepare["txn"] == txn
            st.abort(txn)
            if had_prepare:
                await self._clear_prepare(st, silo)
        self._txn_joined.discard(txn)
