"""Transactional grain state: versioned values with 2PC participation.

Re-design of /root/reference/src/Orleans.Transactions/State/
TransactionalState.cs:611 (ITransactionalState<T> — versioned copies per
transaction, read-version validation, prepare/commit/abort participation)
plus the grain-facing facet. The reference validates at a central TM with
version ranges; here validation is pushed to the participant (optimistic
read-version check + short prepare lock), with the TM (manager.py) running
the 2PC rounds — same outcome: serializable multi-grain transactions.

Usage::

    class AccountGrain(TransactionalGrain):
        def __init__(self):
            super().__init__()
            self.balance = TransactionalState("balance", default=0)

        @transactional
        async def deposit(self, amount):
            v = await self.balance.get()
            await self.balance.set(v + amount)
"""

from __future__ import annotations

import time
from typing import Any

from ..core.errors import TransactionAbortedError
from ..core.serialization import deep_copy
from ..runtime.grain import Grain, always_interleave
from .context import ambient_txn

__all__ = ["TransactionalState", "TransactionalGrain"]

PREPARE_LOCK_TTL = 10.0  # steal an expired lock: TM died mid-2PC


class TransactionalState:
    """One versioned value owned by a grain."""

    def __init__(self, name: str, default: Any = None,
                 storage_name: str = "Default"):
        self.name = name
        self.default = default
        self.storage_name = storage_name
        self.committed: Any = default
        self.committed_version: int = 0
        self.owner: "TransactionalGrain | None" = None
        # txn id -> {"value", "read_version", "written"}
        self.workspace: dict[str, dict] = {}
        self.lock: tuple[str, float] | None = None  # (txn id, deadline)
        self._etag: str | None = None  # storage etag of the committed row

    # -- grain-facing API (PerformRead/PerformUpdate) -------------------
    async def get(self) -> Any:
        info = ambient_txn()
        if info is None:
            return deep_copy(self.committed)
        ws = await self._enter(info)
        return ws["value"]

    async def set(self, value: Any) -> None:
        info = ambient_txn()
        if info is None:
            raise TransactionAbortedError(
                f"state {self.name!r} can only be written inside a "
                "transaction (wrap the method with @transactional)")
        ws = await self._enter(info)
        ws["value"] = value
        ws["written"] = True

    async def _enter(self, info) -> dict:
        ws = self.workspace.get(info.id)
        if ws is None:
            self.owner._txn_join(info)
            ws = self.workspace[info.id] = {
                "value": deep_copy(self.committed),
                "read_version": self.committed_version,
                "written": False,
            }
        return ws

    # -- 2PC participation ----------------------------------------------
    def prepare(self, txn: str, now: float) -> bool:
        ws = self.workspace.get(txn)
        if ws is None:
            return True  # joined via another state of the same grain
        if self.lock is not None and self.lock[1] > now and \
                self.lock[0] != txn:
            return False  # another transaction is mid-commit on this state
        if ws["read_version"] != self.committed_version:
            return False  # someone committed since we read
        self.lock = (txn, now + PREPARE_LOCK_TTL)
        return True

    def commit(self, txn: str, commit_version: int) -> bool:
        """Apply; returns True when the value changed (needs persist)."""
        ws = self.workspace.pop(txn, None)
        if self.lock is not None and self.lock[0] == txn:
            self.lock = None
        if ws is None or not ws["written"]:
            return False
        self.committed = ws["value"]
        self.committed_version = commit_version
        return True

    def abort(self, txn: str) -> None:
        self.workspace.pop(txn, None)
        if self.lock is not None and self.lock[0] == txn:
            self.lock = None


class TransactionalGrain(Grain):
    """Base for grains holding TransactionalState: wires state discovery,
    persistence, and the 2PC surface the TM calls (the participant half of
    TransactionAgent.cs:98)."""

    @property
    def _txn_joined(self) -> set[str]:
        # lazy so subclasses need not call super().__init__()
        return self.__dict__.setdefault("_txn_joined_set", set())

    def _txn_states(self) -> list[TransactionalState]:
        out = []
        for v in vars(self).values():
            if isinstance(v, TransactionalState):
                if v.owner is None:
                    v.owner = self
                out.append(v)
        return out

    # -- lifecycle: recover committed values from storage ----------------
    async def on_activate(self) -> None:
        silo = self._activation.runtime
        for st in self._txn_states():
            provider = silo.storage_manager.get(st.storage_name)
            if provider is None:
                continue
            data, etag = await provider.read(
                self._txn_storage_type(st), self.grain_id)
            st._etag = etag
            if data is not None:
                st.committed = data["value"]
                st.committed_version = data["version"]

    def _txn_storage_type(self, st: TransactionalState) -> str:
        return f"txn:{type(self).__name__}:{st.name}"

    # -- join: register into the ambient participant set (caller-side
    # collection — zero TM round trips; the set rides back to the root
    # on response headers, see transactions/context.py) ------------------
    def _txn_join(self, info) -> None:
        if info.id in self._txn_joined:
            return
        self._txn_joined.add(info.id)
        info.join(self.grain_id, type(self).__name__)

    # -- 2PC surface called by the TM (interleave: the root caller is
    # blocked awaiting commit while these arrive) ------------------------
    @always_interleave
    async def _txn_prepare(self, txn: str) -> bool:
        now = time.time()
        votes = [st.prepare(txn, now) for st in self._txn_states()]
        if not all(votes):
            for st in self._txn_states():
                st.abort(txn)
            self._txn_joined.discard(txn)
            return False
        return True

    @always_interleave
    async def _txn_commit(self, txn: str, commit_version: int) -> None:
        silo = self._activation.runtime
        for st in self._txn_states():
            if st.commit(txn, commit_version):
                provider = silo.storage_manager.get(st.storage_name)
                if provider is not None:
                    st._etag = await provider.write(
                        self._txn_storage_type(st), self.grain_id,
                        {"value": st.committed,
                         "version": st.committed_version},
                        etag=st._etag)
        self._txn_joined.discard(txn)

    @always_interleave
    async def _txn_abort(self, txn: str) -> None:
        for st in self._txn_states():
            st.abort(txn)
        self._txn_joined.discard(txn)
