"""Transaction manager + per-silo agent + the @transactional scope.

Re-design of /root/reference/src/Orleans.Transactions/InClusterTM/
TransactionManager.cs:709 (in-cluster sequencer + commit log),
src/Orleans.Runtime/Transactions/TransactionAgent.cs:98 (per-silo agent),
and TransactionLog.cs (durable commit log — see log.py).

Departures from the 2.0-preview reference, for throughput:

- **Zero-chatter starts/joins.** Starting a transaction and joining
  participants are silo-local (the TransactionInfo rides requests via
  RequestContext; callee joins ride back on response headers — see
  context.py). The TM hears about a transaction exactly once, at commit,
  with the full participant set — one grain call per transaction instead
  of 2+P.
- **Sharded, reentrant TMs.** N TM grains (txn-id hash picks one), each
  ``@reentrant`` so hundreds of 2PC rounds interleave on the mailbox
  instead of serializing behind one in-flight commit. Commit versions are
  shard-namespaced (version ≡ shard (mod n_shards)) so they stay globally
  distinct while each shard's sequence is monotone — all the
  read-version validation in state.py needs.
- **Gathered 2PC rounds.** Prepare / commit-apply / abort fan out with
  ``asyncio.gather`` instead of sequential awaits.
- **Write-ahead decision log.** A decision is durable (appended + synced
  via the TransactionLog provider) BEFORE any participant learns it;
  a recovered TM replays the log, so in-doubt participants resolve via
  ``decision_of`` after a TM silo dies (the recovery contract of
  TransactionLog.cs + TransactionManager.cs checkpointing).
"""

from __future__ import annotations

import asyncio
import functools
import logging
import random
import time
from typing import TYPE_CHECKING

from ..core.errors import (TransactionAbortedError, TransactionConflictError,
                           TransactionError)
from ..core.ids import GrainId
from ..runtime.grain import Grain, reentrant
from .context import (
    TransactionInfo,
    ambient_txn,
    clear_ambient_txn,
    set_ambient_txn,
)
from .log import InMemoryTransactionLog, TransactionLog

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.transactions")

__all__ = ["TransactionManagerGrain", "TransactionAgent", "transactional",
           "add_transactions"]

DEFAULT_TXN_TIMEOUT = 10.0
DEFAULT_TM_SHARDS = 4
# undelivered-outcome redelivery cadence + log compaction policy
RETRY_PERIOD = 0.5
ACK_RETENTION = 30.0       # keep acked decisions this long for duplicate
                           # client retries (well past PREPARE_LOCK_TTL)
COMPACT_MIN_PRUNABLE = 256


@reentrant
class TransactionManagerGrain(Grain):
    """One TM shard (grain key = shard index): sequencer + 2PC
    coordinator over a durable decision log. Reentrant: concurrent
    commits interleave across their prepare/apply awaits."""

    def __init__(self) -> None:
        self._seq: int | None = None       # last version this shard issued
        # txn -> (decision, commit_version); version 0 for aborts
        self._decisions: dict[str, tuple[str, int]] = {}
        self._deciding: dict[str, asyncio.Future] = {}
        # txn -> [(gid, iface, method, args)] outcome notifications that
        # failed delivery; re-driven by the redelivery worker so a
        # participant that missed its commit never holds a stale lock
        # past one retry period (TransactionManager.cs:709's notification
        # re-drive)
        self._undelivered: dict[str, list] = {}
        # txn -> monotonic time every participant acked the outcome;
        # compaction prunes acked decisions after ACK_RETENTION
        self._acked_at: dict[str, float] = {}
        self._worker: asyncio.Task | None = None
        # compaction barrier: while set, new decisions wait and the
        # compactor waits for in-flight appends — otherwise a decision
        # logged during the rewrite is erased from both disk and memory
        self._compact_gate: asyncio.Event | None = None
        self._appends_inflight = 0

    @property
    def _cfg(self) -> "TransactionAgent":
        agent = self._activation.runtime.transactions
        if agent is None:
            raise TransactionError("no transaction agent installed")
        return agent

    async def on_activate(self) -> None:
        # recovery: replay the durable log (TM failover — the new
        # activation continues the shard's sequence and can answer
        # decision_of for every past transaction)
        shard = int(self.grain_id.key)
        self._seq, self._decisions = await self._cfg.log.replay(shard)
        if self._decisions:
            log.info("TM shard %d recovered %d decisions (seq=%d)",
                     shard, len(self._decisions), self._seq)
            # replayed decisions are already-settled history: their
            # participants resolved (or will via decision_of) long ago.
            # Mark them acked from replay time so compaction's retention
            # window still bounds the log after a failover.
            now = time.monotonic()
            for txn in self._decisions:
                self._acked_at.setdefault(txn, now)
        self._worker = asyncio.ensure_future(self._redelivery_loop())

    async def on_deactivate(self) -> None:
        if self._worker is not None:
            self._worker.cancel()
            try:
                await self._worker
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._worker = None

    async def commit_transaction(self, txn: str, participants: list,
                                 deadline: float) -> bool:
        """The whole 2PC: prepare round → durable decision → apply round.
        ``participants``: [(GrainId, interface_name)] collected by the
        caller's agent."""
        prior = self._decisions.get(txn)
        if prior is not None:
            # duplicate commit (client resend — e.g. the original TM
            # incarnation died between logging the decision and finishing
            # the fanout): the decision stands, but the outcome must be
            # RE-DRIVEN — participants may never have heard it, and the
            # old incarnation's undelivered-outcome queue died with it.
            # Deliveries are idempotent (applied txns no-op).
            if prior[0] == "committed":
                await self._fanout(txn, participants, "_txn_commit", txn,
                                   prior[1])
                return True
            await self._fanout(txn, participants, "_txn_abort", txn)
            return False
        if time.time() > deadline:
            decision, version = await self._decide(txn, "aborted")
            if decision == "committed":
                # a duplicate incarnation already committed this txn: the
                # log's decision stands regardless of our local deadline
                await self._fanout(txn, participants, "_txn_commit", txn,
                                   version)
                return True
            await self._fanout(txn, participants, "_txn_abort", txn)
            return False
        votes = await _collect(
            [self._call(gid, iface, "_txn_prepare", txn)
             for gid, iface in participants])
        if all(v is True for v in votes):
            shard = int(self.grain_id.key)
            n = self._cfg.shards
            # shard-namespaced monotone sequence, reserved synchronously
            # (no await between read and advance): globally distinct
            self._seq = (self._seq + n) if self._seq else (shard + n)
            decision, version = await self._decide(txn, "committed",
                                                   self._seq)
            if decision == "committed":
                await self._fanout(txn, participants, "_txn_commit", txn,
                                   version)
                return True
            await self._fanout(txn, participants, "_txn_abort", txn)
            return False
        decision, version = await self._decide(txn, "aborted")
        if decision == "committed":      # lost race with a duplicate commit
            await self._fanout(txn, participants, "_txn_commit", txn, version)
            return True
        await self._fanout(txn, participants, "_txn_abort", txn)
        return False

    async def abort_transaction(self, txn: str, participants: list) -> None:
        decision, version = await self._decide(txn, "aborted")
        if decision == "committed":
            # late/duplicate abort for an already-committed txn: the
            # logged decision wins — redeliver the commit instead of
            # overwriting it (a recovered TM must never replay a commit
            # as an abort)
            await self._fanout(txn, participants, "_txn_commit", txn, version)
            return
        await self._fanout(txn, participants, "_txn_abort", txn)

    async def decision_of(self, txn: str,
                          resolve: bool = False) -> tuple[str, int] | None:
        """(decision, commit_version) or None. The version lets an
        in-doubt participant apply a missed commit, not just learn of it.

        ``resolve=True`` (participant in-doubt resolution) makes presumed
        abort DURABLE: an unknown transaction is logged as aborted before
        answering, so a commit racing this inquiry (e.g. a 2PC whose vote
        gather outlived the prepare-lock TTL) loses to the recorded abort
        instead of committing on participants that already dropped their
        prepares."""
        prior = self._decisions.get(txn)
        if prior is not None:
            return prior
        pending = self._deciding.get(txn)
        if pending is not None:
            return await pending
        if resolve:
            rec = await self._decide(txn, "aborted")
            # the inquiring participant IS the resolution — no fanout
            # will ever ack this record, so mark it prunable now
            self._acked_at[txn] = time.monotonic()
            return rec
        return None

    # -- internals -------------------------------------------------------
    async def _decide(self, txn: str, decision: str,
                      version: int = 0) -> tuple[str, int]:
        """Write-ahead: the log append IS the commit point
        (TransactionLog.cs) — participants are only told afterwards.
        Idempotent: a prior decision (in-memory or being appended) always
        wins; returns the winning (decision, version)."""
        prior = self._decisions.get(txn)
        if prior is not None:
            return prior
        pending = self._deciding.get(txn)
        if pending is not None:          # concurrent commit/abort race
            return await pending
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._deciding[txn] = fut
        try:
            while self._compact_gate is not None:   # wait out compaction
                await self._compact_gate.wait()
            self._appends_inflight += 1
            try:
                # first-decision-wins at the LOG, not just this
                # activation's memory: a concurrent duplicate TM
                # incarnation (membership-transition window) may have
                # already decided this txn — its record must win or a
                # presumed abort could race a commit onto different
                # participants
                rec = await self._cfg.log.decide(
                    int(self.grain_id.key), txn, decision, version)
            finally:
                self._appends_inflight -= 1
            if rec[1] > (self._seq or 0):
                # the winning record came from another incarnation ahead
                # of us: adopt its sequence so later commits stay monotone
                # (same shard → same residue mod n_shards, congruence holds)
                self._seq = rec[1]
            self._decisions[txn] = rec
            fut.set_result(rec)
            return rec
        except BaseException as e:
            fut.set_exception(e)
            fut.exception()              # consumed; avoid unretrieved warn
            raise
        finally:
            self._deciding.pop(txn, None)

    async def _fanout(self, txn: str, participants: list, method: str,
                      *args) -> None:
        failed: list = []
        outcomes = await _collect(
            [self._call(gid, iface, method, *args)
             for gid, iface in participants])
        for (gid, iface), out in zip(participants, outcomes):
            if isinstance(out, BaseException):
                # decision is logged; queue for redelivery (plus
                # participant-side decision_of resolution on lock
                # expiry / reactivation)
                log.warning("%s delivery failed for %s: %r", method, gid,
                            out)
                failed.append((gid, iface, method, args))
        if failed:
            self._undelivered.setdefault(txn, []).extend(failed)
        else:
            self._acked_at[txn] = time.monotonic()

    async def _redelivery_loop(self) -> None:
        """Re-drive undelivered outcome notifications and compact the
        decision log once acked decisions age out (the reference's
        truncation below the stable mark)."""
        while True:
            await asyncio.sleep(RETRY_PERIOD)
            if self._activation.runtime.status not in ("Running", "Joining"):
                return  # silo killed/stopped: a dead silo must not keep
                        # driving 2PC outcomes (on_deactivate never ran)
            for txn in list(self._undelivered):
                queue = self._undelivered.pop(txn, [])
                still: list = []
                for gid, iface, method, args in queue:
                    try:
                        await self._call(gid, iface, method, *args)
                    except Exception:  # noqa: BLE001
                        still.append((gid, iface, method, args))
                if still:
                    self._undelivered[txn] = still
                else:
                    self._acked_at[txn] = time.monotonic()
            await self._maybe_compact()

    async def _maybe_compact(self) -> None:
        now = time.monotonic()
        prunable = [t for t, at in self._acked_at.items()
                    if now - at > ACK_RETENTION]
        if len(prunable) < COMPACT_MIN_PRUNABLE:
            return
        if self._compact_gate is not None:
            return
        gate = self._compact_gate = asyncio.Event()
        try:
            # quiesce: no snapshot until in-flight appends land, and no
            # new appends until the rewrite finishes (the gate in _decide)
            while self._appends_inflight:
                await asyncio.sleep(0.001)  # executor fsync may take ms
            pruned = set(prunable)
            live = {t: d for t, d in self._decisions.items()
                    if t not in pruned}
            await self._cfg.log.rewrite(int(self.grain_id.key), live,
                                        self._seq or 0)
            self._decisions = live
            for t in prunable:
                self._acked_at.pop(t, None)
            log.info("TM shard %s compacted %d decisions (%d live)",
                     self.grain_id.key, len(prunable), len(live))
        finally:
            self._compact_gate = None
            gate.set()

    def _call(self, grain_id: GrainId, iface: str, method: str, *args):
        silo = self._activation.runtime
        direct = silo.runtime_client.try_direct_interleave(
            grain_id, method, args, {})
        if direct is not None:
            return direct
        cls = silo.registry.resolve(iface)
        if cls is None:
            raise TransactionError(f"participant class {iface} unknown")
        return silo.runtime_client.send_request(
            target_grain=grain_id, grain_class=cls, interface_name=iface,
            method_name=method, args=args, kwargs={},
            is_always_interleave=True)


async def _collect(calls: list) -> list:
    """Await every call, mapping exceptions to values (the
    gather(return_exceptions=True) contract) WITHOUT wrapping each call
    in a Task: the 2PC rounds are mostly direct local coroutines, where
    sequential awaits do the same work minus a task creation per
    participant; remote calls are already-transmitted futures, so their
    round trips still overlap."""
    out = []
    for idx, c in enumerate(calls):
        try:
            out.append(await c)
        except asyncio.CancelledError:
            # parent turn cancelled (silo stop/kill): propagate — a
            # cancelled 2PC round must not keep driving the protocol
            # against a tearing-down runtime. Close not-yet-awaited
            # coroutines so they don't leak "never awaited" warnings.
            for rest in calls[idx + 1:]:
                if asyncio.iscoroutine(rest):
                    rest.close()
            raise
        except BaseException as e:  # noqa: BLE001
            out.append(e)
    return out


class TransactionAgent:
    """Per-silo agent (TransactionAgent.cs:98): creates transaction scopes
    locally and routes commits to the txn's TM shard; installed as
    ``silo.transactions``."""

    def __init__(self, silo: "Silo", log_provider: TransactionLog,
                 shards: int):
        self.silo = silo
        self.log = log_provider
        self.shards = shards

    def _tm_call(self, txn_id: str, method: str, *args):
        """Route to the txn's TM shard: direct coroutine when the shard's
        activation is local (the TM is reentrant), message otherwise."""
        from ..runtime.grain import grain_type_of
        shard = int(txn_id[:8], 16) % self.shards
        gid = GrainId.for_grain(grain_type_of(TransactionManagerGrain),
                                shard)
        direct = self.silo.runtime_client.try_direct_interleave(
            gid, method, args, {})
        if direct is not None:
            return direct
        ref = self.silo.grain_factory.get_grain(
            TransactionManagerGrain, shard)
        return getattr(ref, method)(*args)

    def start(self, timeout: float = DEFAULT_TXN_TIMEOUT,
              priority_ts: tuple | None = None) -> TransactionInfo:
        """Silo-local: no TM round trip (the agent-collected design).
        ``priority_ts`` carries a retrying transaction's original wound-wait
        priority so it ages instead of rejuvenating."""
        self.silo.stats.increment("transactions.started")
        return TransactionInfo(deadline=time.time() + timeout,
                               ts=priority_ts)

    async def commit(self, info: TransactionInfo) -> bool:
        ok = await self._tm_call(info.id, "commit_transaction", info.id,
                                 list(info.participants.values()),
                                 info.deadline)
        self.silo.stats.increment(
            "transactions.committed" if ok else "transactions.aborted")
        return ok

    async def abort(self, info: TransactionInfo) -> None:
        self.silo.stats.increment("transactions.aborted")
        await self._tm_call(info.id, "abort_transaction", info.id,
                            list(info.participants.values()))

    async def decision_of(self, txn_id: str,
                          resolve: bool = False) -> tuple[str, int] | None:
        return await self._tm_call(txn_id, "decision_of", txn_id, resolve)


def transactional(fn=None, *, option: str = "required"):
    """Method decorator opening a transaction scope ([Transaction(...)];
    scope semantics of InsideRuntimeClient.Invoke:313-438).

    options: "required" (join ambient or start new — default),
    "requires_new" (always start a fresh transaction),
    "suppress" (run outside any transaction).
    """

    def deco(fn):
        @functools.wraps(fn)
        async def wrapper(self, *args, **kwargs):
            cur = ambient_txn()
            if option == "suppress":
                clear_ambient_txn()
                try:
                    return await fn(self, *args, **kwargs)
                finally:
                    if cur is not None:
                        set_ambient_txn(cur)
            if cur is not None and option == "required":
                return await fn(self, *args, **kwargs)  # join ambient scope
            agent = self._activation.runtime.transactions
            if agent is None:
                raise TransactionError(
                    "no transaction agent installed (add_transactions)")
            # Root scope: conflicts retry until the original deadline.
            # Wait-die entry (state.TransactionalState._enter) makes
            # conflicts surface EARLY as TransactionConflictError —
            # before any doomed prepare/commit round — and retries reuse
            # the original priority ts so the transaction ages into the
            # winner. Validation aborts at commit (the read-version
            # safety net) retry the same way. Application exceptions
            # abort once and propagate.
            retry_deadline = time.time() + DEFAULT_TXN_TIMEOUT
            attempt = 0
            priority_ts = None
            while True:
                info = agent.start(priority_ts=priority_ts)
                priority_ts = info.ts
                set_ambient_txn(info)
                try:
                    result = await fn(self, *args, **kwargs)
                except TransactionConflictError:
                    clear_ambient_txn()
                    await agent.abort(info)  # release everything we hold
                    attempt += 1
                    if time.time() >= retry_deadline:
                        raise
                    # brief jittered pause: the older holder we died
                    # against is typically mid-2PC; let it finish
                    await asyncio.sleep(0.0003 * (0.5 + random.random()))
                    continue
                except BaseException:
                    clear_ambient_txn()
                    await agent.abort(info)
                    raise
                clear_ambient_txn()
                if await agent.commit(info):
                    return result
                attempt += 1
                if time.time() >= retry_deadline:
                    raise TransactionAbortedError(
                        f"transaction {info.id} aborted after {attempt} "
                        "attempts (conflict or participant failure)")
                # jittered backoff: colliding retries must desynchronize
                await asyncio.sleep(
                    min(0.0005 * (2 ** min(attempt, 5)), 0.01)
                    * (0.5 + random.random()))

        wrapper.__orleans_transaction__ = option
        # Transactional calls interleave (the reference marks transactional
        # methods interleavable for exactly this reason): a lock wait inside
        # TransactionalState._enter must suspend only ITS transaction, not
        # the activation's whole mailbox — otherwise waits-for edges form
        # through turn queues where wound-wait cannot see or break them.
        # Isolation is the transactional states' job (workspace exclusivity
        # + read-version validation), not the turn gate's.
        #
        # SEMANTIC CAVEAT (divergence from the reference, which marks only
        # the 2PC participant-extension methods [AlwaysInterleave]): plain
        # instance attributes touched inside a @transactional method are
        # NOT turn-protected — two transactions on the same activation can
        # interleave at any await, so read-modify-write of ordinary fields
        # can race. Keep all transactional data in TransactionalState
        # facets (which serialize through the wound-wait lock); plain
        # fields inside transactional methods are safe only for
        # idempotent/monotonic writes. Documented in MIGRATION.md.
        wrapper.__orleans_always_interleave__ = True
        return wrapper

    return deco(fn) if fn is not None else deco


def add_transactions(builder, log_provider: TransactionLog | None = None,
                     shards: int = DEFAULT_TM_SHARDS):
    """Register the TM shard grains + install the per-silo agent.

    ``log_provider``: durable commit log (default: in-memory — share one
    instance across silos for TM failover in tests; use File/Sqlite for
    real durability). ``shards``: number of TM grains commits spread over.
    """
    builder.add_grains(TransactionManagerGrain)
    log_provider = log_provider or InMemoryTransactionLog()

    def install(silo) -> None:
        silo.transactions = TransactionAgent(silo, log_provider, shards)

    return builder.configure(install)
