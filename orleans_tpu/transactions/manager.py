"""Transaction manager + per-silo agent + the @transactional scope.

Re-design of /root/reference/src/Orleans.Transactions/InClusterTM/
TransactionManager.cs:709 (in-cluster sequencer + commit log),
src/Orleans.Runtime/Transactions/TransactionAgent.cs:98 (per-silo agent),
and TransactionLog.cs (durable commit log — see log.py).

Departures from the 2.0-preview reference, for throughput:

- **Zero-chatter starts/joins.** Starting a transaction and joining
  participants are silo-local (the TransactionInfo rides requests via
  RequestContext; callee joins ride back on response headers — see
  context.py). The TM hears about a transaction exactly once, at commit,
  with the full participant set — one grain call per transaction instead
  of 2+P.
- **Sharded, reentrant TMs.** N TM grains (txn-id hash picks one), each
  ``@reentrant`` so hundreds of 2PC rounds interleave on the mailbox
  instead of serializing behind one in-flight commit. Commit versions are
  shard-namespaced (version ≡ shard (mod n_shards)) so they stay globally
  distinct while each shard's sequence is monotone — all the
  read-version validation in state.py needs.
- **Gathered 2PC rounds.** Prepare / commit-apply / abort fan out with
  ``asyncio.gather`` instead of sequential awaits.
- **Write-ahead decision log.** A decision is durable (appended + synced
  via the TransactionLog provider) BEFORE any participant learns it;
  a recovered TM replays the log, so in-doubt participants resolve via
  ``decision_of`` after a TM silo dies (the recovery contract of
  TransactionLog.cs + TransactionManager.cs checkpointing).
"""

from __future__ import annotations

import asyncio
import functools
import logging
import time
from typing import TYPE_CHECKING

from ..core.errors import TransactionAbortedError, TransactionError
from ..core.ids import GrainId
from ..runtime.grain import Grain, reentrant
from .context import (
    TransactionInfo,
    ambient_txn,
    clear_ambient_txn,
    set_ambient_txn,
)
from .log import InMemoryTransactionLog, TransactionLog

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.transactions")

__all__ = ["TransactionManagerGrain", "TransactionAgent", "transactional",
           "add_transactions"]

DEFAULT_TXN_TIMEOUT = 10.0
DEFAULT_TM_SHARDS = 4


@reentrant
class TransactionManagerGrain(Grain):
    """One TM shard (grain key = shard index): sequencer + 2PC
    coordinator over a durable decision log. Reentrant: concurrent
    commits interleave across their prepare/apply awaits."""

    def __init__(self) -> None:
        self._seq: int | None = None       # last version this shard issued
        self._decisions: dict[str, str] = {}

    @property
    def _cfg(self) -> "TransactionAgent":
        agent = self._activation.runtime.transactions
        if agent is None:
            raise TransactionError("no transaction agent installed")
        return agent

    async def on_activate(self) -> None:
        # recovery: replay the durable log (TM failover — the new
        # activation continues the shard's sequence and can answer
        # decision_of for every past transaction)
        shard = int(self.grain_id.key)
        self._seq, self._decisions = await self._cfg.log.replay(shard)
        if self._decisions:
            log.info("TM shard %d recovered %d decisions (seq=%d)",
                     shard, len(self._decisions), self._seq)

    async def commit_transaction(self, txn: str, participants: list,
                                 deadline: float) -> bool:
        """The whole 2PC: prepare round → durable decision → apply round.
        ``participants``: [(GrainId, interface_name)] collected by the
        caller's agent."""
        prior = self._decisions.get(txn)
        if prior is not None:            # duplicate commit (client retry)
            return prior == "committed"
        if time.time() > deadline:
            await self._decide(txn, "aborted")
            await self._fanout(participants, "_txn_abort", txn)
            return False
        votes = await asyncio.gather(
            *(self._call(gid, iface, "_txn_prepare", txn)
              for gid, iface in participants),
            return_exceptions=True)
        if all(v is True for v in votes):
            shard = int(self.grain_id.key)
            n = self._cfg.shards
            # shard-namespaced monotone sequence: globally distinct
            self._seq = (self._seq + n) if self._seq else (shard + n)
            version = self._seq
            await self._decide(txn, "committed", version)
            await self._fanout(participants, "_txn_commit", txn, version)
            return True
        await self._decide(txn, "aborted")
        await self._fanout(participants, "_txn_abort", txn)
        return False

    async def abort_transaction(self, txn: str, participants: list) -> None:
        await self._decide(txn, "aborted")
        await self._fanout(participants, "_txn_abort", txn)

    async def decision_of(self, txn: str) -> str | None:
        return self._decisions.get(txn)

    # -- internals -------------------------------------------------------
    async def _decide(self, txn: str, decision: str,
                      version: int = 0) -> None:
        """Write-ahead: the log append IS the commit point
        (TransactionLog.cs) — participants are only told afterwards."""
        await self._cfg.log.append(int(self.grain_id.key), txn, decision,
                                   version)
        self._decisions[txn] = decision

    async def _fanout(self, participants: list, method: str, *args) -> None:
        async def one(gid, iface):
            try:
                await self._call(gid, iface, method, *args)
            except Exception:  # noqa: BLE001 — decision is logged; the
                # participant re-syncs from storage/decision_of on
                # reactivation (lock-TTL steal covers stuck prepares)
                log.warning("%s delivery failed for %s", method, gid,
                            exc_info=True)

        await asyncio.gather(*(one(gid, iface)
                               for gid, iface in participants))

    def _call(self, grain_id: GrainId, iface: str, method: str, *args):
        silo = self._activation.runtime
        direct = _local_always_interleave_call(silo, grain_id, method, args)
        if direct is not None:
            return direct
        cls = silo.registry.resolve(iface)
        if cls is None:
            raise TransactionError(f"participant class {iface} unknown")
        return silo.runtime_client.send_request(
            target_grain=grain_id, grain_class=cls, interface_name=iface,
            method_name=method, args=args, kwargs={},
            is_always_interleave=True)


def _local_always_interleave_call(silo, grain_id: GrainId, method: str,
                                  args: tuple):
    """In-silo fast path for the transaction protocol's internal calls
    (TM→participant 2PC rounds, agent→TM commits): the target methods are
    always-interleave (participants) or on a reentrant grain (the TM), so
    the mailbox gate would admit them unconditionally — invoking the local
    activation's coroutine directly preserves turn semantics while
    skipping the per-message machinery. The reference's agent reaches its
    in-silo TM the same way (TransactionAgent.cs — direct component
    calls, not remote messages). Args here are ids/ints (immutables), so
    deep-copy isolation is preserved trivially. Returns None when the
    activation is not local (the ordinary messaging path applies)."""
    acts = silo.catalog.by_grain.get(grain_id)
    if not acts or len(acts) != 1:
        return None
    act = acts[0]
    from ..runtime.activation import ActivationState
    if act.state != ActivationState.VALID:
        return None
    act.last_busy = time.monotonic()   # keep the idle collector away
    return getattr(act.grain_instance, method)(*args)


class TransactionAgent:
    """Per-silo agent (TransactionAgent.cs:98): creates transaction scopes
    locally and routes commits to the txn's TM shard; installed as
    ``silo.transactions``."""

    def __init__(self, silo: "Silo", log_provider: TransactionLog,
                 shards: int):
        self.silo = silo
        self.log = log_provider
        self.shards = shards

    def _tm_call(self, txn_id: str, method: str, *args):
        """Route to the txn's TM shard: direct coroutine when the shard's
        activation is local (the TM is reentrant), message otherwise."""
        from ..runtime.grain import grain_type_of
        shard = int(txn_id[:8], 16) % self.shards
        gid = GrainId.for_grain(grain_type_of(TransactionManagerGrain),
                                shard)
        direct = _local_always_interleave_call(self.silo, gid, method, args)
        if direct is not None:
            return direct
        ref = self.silo.grain_factory.get_grain(
            TransactionManagerGrain, shard)
        return getattr(ref, method)(*args)

    def start(self, timeout: float = DEFAULT_TXN_TIMEOUT) -> TransactionInfo:
        """Silo-local: no TM round trip (the agent-collected design)."""
        self.silo.stats.increment("transactions.started")
        return TransactionInfo(deadline=time.time() + timeout)

    async def commit(self, info: TransactionInfo) -> bool:
        ok = await self._tm_call(info.id, "commit_transaction", info.id,
                                 list(info.participants.values()),
                                 info.deadline)
        self.silo.stats.increment(
            "transactions.committed" if ok else "transactions.aborted")
        return ok

    async def abort(self, info: TransactionInfo) -> None:
        self.silo.stats.increment("transactions.aborted")
        await self._tm_call(info.id, "abort_transaction", info.id,
                            list(info.participants.values()))

    async def decision_of(self, txn_id: str) -> str | None:
        return await self._tm_call(txn_id, "decision_of", txn_id)


def transactional(fn=None, *, option: str = "required"):
    """Method decorator opening a transaction scope ([Transaction(...)];
    scope semantics of InsideRuntimeClient.Invoke:313-438).

    options: "required" (join ambient or start new — default),
    "requires_new" (always start a fresh transaction),
    "suppress" (run outside any transaction).
    """

    def deco(fn):
        @functools.wraps(fn)
        async def wrapper(self, *args, **kwargs):
            cur = ambient_txn()
            if option == "suppress":
                clear_ambient_txn()
                try:
                    return await fn(self, *args, **kwargs)
                finally:
                    if cur is not None:
                        set_ambient_txn(cur)
            if cur is not None and option == "required":
                return await fn(self, *args, **kwargs)  # join ambient scope
            agent = self._activation.runtime.transactions
            if agent is None:
                raise TransactionError(
                    "no transaction agent installed (add_transactions)")
            info = agent.start()
            set_ambient_txn(info)
            try:
                result = await fn(self, *args, **kwargs)
            except BaseException:
                clear_ambient_txn()
                await agent.abort(info)
                raise
            clear_ambient_txn()
            if not await agent.commit(info):
                raise TransactionAbortedError(
                    f"transaction {info.id} aborted (conflict or "
                    "participant failure)")
            return result

        wrapper.__orleans_transaction__ = option
        return wrapper

    return deco(fn) if fn is not None else deco


def add_transactions(builder, log_provider: TransactionLog | None = None,
                     shards: int = DEFAULT_TM_SHARDS):
    """Register the TM shard grains + install the per-silo agent.

    ``log_provider``: durable commit log (default: in-memory — share one
    instance across silos for TM failover in tests; use File/Sqlite for
    real durability). ``shards``: number of TM grains commits spread over.
    """
    builder.add_grains(TransactionManagerGrain)
    log_provider = log_provider or InMemoryTransactionLog()

    def install(silo) -> None:
        silo.transactions = TransactionAgent(silo, log_provider, shards)

    return builder.configure(install)
