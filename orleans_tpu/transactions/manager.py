"""Transaction manager + per-silo agent + the @transactional scope.

Re-design of /root/reference/src/Orleans.Transactions/InClusterTM/
TransactionManager.cs:709 (in-cluster sequencer + commit log),
src/Orleans.Runtime/Transactions/TransactionAgent.cs:98 (per-silo proxy to
the TM), and TransactionLog.cs. The TM here is a singleton grain running
2PC over participants that registered via join; commit versions are the
TM's monotone sequence (the sequencer), and the decision log is grain state
(the commit-log analog, durable through the grain's storage provider).
"""

from __future__ import annotations

import functools
import logging
import time
import uuid
from typing import TYPE_CHECKING

from ..core.errors import TransactionAbortedError, TransactionError
from ..core.ids import GrainId
from ..runtime.grain import StatefulGrain
from .context import ambient_txn, clear_ambient_txn, set_ambient_txn

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.transactions")

__all__ = ["TransactionManagerGrain", "TransactionAgent", "transactional",
           "add_transactions"]

DEFAULT_TXN_TIMEOUT = 10.0


class TransactionManagerGrain(StatefulGrain):
    """Singleton TM grain (key 0): sequencer + 2PC coordinator + decision
    log. State: {"seq": int, "decisions": {txn: "committed"|"aborted"}}."""

    def _active(self) -> dict:
        return self.state.setdefault("active", {})

    async def start_transaction(self, timeout: float = DEFAULT_TXN_TIMEOUT
                                ) -> str:
        txn = uuid.uuid4().hex
        self._active()[txn] = {
            "participants": {},        # str(grain_id) -> (GrainId, iface)
            "deadline": time.time() + timeout,
        }
        return txn

    async def join(self, txn: str, grain_id: GrainId, iface: str) -> None:
        info = self._active().get(txn)
        if info is None:
            raise TransactionError(f"transaction {txn} unknown or finished")
        if time.time() > info["deadline"]:
            raise TransactionAbortedError(f"transaction {txn} timed out")
        info["participants"][str(grain_id)] = (grain_id, iface)

    async def commit_transaction(self, txn: str) -> bool:
        info = self._active().pop(txn, None)
        if info is None:
            return False
        if time.time() > info["deadline"]:
            await self._notify(info, "_txn_abort", txn)
            await self._record(txn, "aborted")
            return False
        participants = list(info["participants"].values())
        # phase 1: prepare — every participant validates + locks
        votes = []
        for gid, iface in participants:
            try:
                votes.append(await self._call(gid, iface, "_txn_prepare", txn))
            except Exception:  # noqa: BLE001 — unreachable participant = no
                log.warning("prepare failed for %s in %s", gid, txn,
                            exc_info=True)
                votes.append(False)
        if all(votes):
            # sequencer: commit version = next monotone sequence number
            self.state["seq"] = self.state.get("seq", 0) + 1
            version = self.state["seq"]
            await self._record(txn, "committed")
            for gid, iface in participants:
                try:
                    await self._call(gid, iface, "_txn_commit", txn, version)
                except Exception:  # noqa: BLE001 — decision is logged;
                    # participant re-syncs from storage on reactivation
                    log.warning("commit delivery failed for %s in %s",
                                gid, txn, exc_info=True)
            return True
        await self._notify(info, "_txn_abort", txn)
        await self._record(txn, "aborted")
        return False

    async def abort_transaction(self, txn: str) -> None:
        info = self._active().pop(txn, None)
        if info is not None:
            await self._notify(info, "_txn_abort", txn)
            await self._record(txn, "aborted")

    async def decision_of(self, txn: str) -> str | None:
        return self.state.get("decisions", {}).get(txn)

    # -- internals -------------------------------------------------------
    async def _record(self, txn: str, decision: str) -> None:
        """Append to the decision log and persist (TransactionLog.cs)."""
        self.state.setdefault("decisions", {})[txn] = decision
        active = self.state.pop("active", None)  # volatile: don't persist
        try:
            await self.write_state()
        finally:
            if active is not None:
                self.state["active"] = active

    async def _notify(self, info: dict, method: str, txn: str) -> None:
        for gid, iface in info["participants"].values():
            try:
                await self._call(gid, iface, method, txn)
            except Exception:  # noqa: BLE001
                pass

    def _call(self, grain_id: GrainId, iface: str, method: str, *args):
        silo = self._activation.runtime
        cls = silo.registry.resolve(iface)
        if cls is None:
            raise TransactionError(f"participant class {iface} unknown")
        return silo.runtime_client.send_request(
            target_grain=grain_id, grain_class=cls, interface_name=iface,
            method_name=method, args=args, kwargs={},
            is_always_interleave=True)


class TransactionAgent:
    """Per-silo facade to the TM (TransactionAgent.cs:98); installed as
    ``silo.transactions``."""

    def __init__(self, silo: "Silo"):
        self.silo = silo

    def _tm(self):
        return self.silo.grain_factory.get_grain(TransactionManagerGrain, 0)

    async def start(self, timeout: float = DEFAULT_TXN_TIMEOUT) -> str:
        self.silo.stats.increment("transactions.started")
        return await self._tm().start_transaction(timeout)

    async def join(self, txn: str, grain_id: GrainId, iface: str) -> None:
        await self._tm().join(txn, grain_id, iface)

    async def commit(self, txn: str) -> bool:
        ok = await self._tm().commit_transaction(txn)
        self.silo.stats.increment(
            "transactions.committed" if ok else "transactions.aborted")
        return ok

    async def abort(self, txn: str) -> None:
        self.silo.stats.increment("transactions.aborted")
        await self._tm().abort_transaction(txn)


def transactional(fn=None, *, option: str = "required"):
    """Method decorator opening a transaction scope ([Transaction(...)];
    scope semantics of InsideRuntimeClient.Invoke:313-438).

    options: "required" (join ambient or start new — default),
    "requires_new" (always start a fresh transaction),
    "suppress" (run outside any transaction).
    """

    def deco(fn):
        @functools.wraps(fn)
        async def wrapper(self, *args, **kwargs):
            cur = ambient_txn()
            if option == "suppress":
                clear_ambient_txn()
                try:
                    return await fn(self, *args, **kwargs)
                finally:
                    if cur is not None:
                        set_ambient_txn(cur)
            if cur is not None and option == "required":
                return await fn(self, *args, **kwargs)  # join ambient scope
            agent = self._activation.runtime.transactions
            if agent is None:
                raise TransactionError(
                    "no transaction agent installed (add_transactions)")
            txn = await agent.start()
            set_ambient_txn(txn)
            try:
                result = await fn(self, *args, **kwargs)
            except BaseException:
                clear_ambient_txn()
                await agent.abort(txn)
                raise
            clear_ambient_txn()
            if not await agent.commit(txn):
                raise TransactionAbortedError(
                    f"transaction {txn} aborted (conflict or participant "
                    "failure)")
            return result

        wrapper.__orleans_transaction__ = option
        return wrapper

    return deco(fn) if fn is not None else deco


def add_transactions(builder):
    """Register the TM grain + install the per-silo agent on a SiloBuilder."""
    builder.add_grains(TransactionManagerGrain)

    def install(silo) -> None:
        silo.transactions = TransactionAgent(silo)

    return builder.configure(install)
