"""OTPU002 blocking-in-turn and OTPU003 interleaving-hazard.

Turn discipline: a grain/runtime turn is one coroutine sharing the silo's
event loop with every other activation. A synchronous block inside an
``async def`` (``time.sleep``, sync socket/file IO, ``Future.result()``)
stalls the whole silo, not one grain (OTPU002). And in a non-reentrant
grain the author assumes no interleaving — but ``always_interleave``
methods, call-chain reentrancy, read-only interleaving, and timer turns
can all run between an ``await`` and the code after it, so instance state
written before an await must be re-validated when read after it
(OTPU003).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..model import FileContext, Finding, Rule, register
from .common import (
    decorator_names,
    dotted_name,
    is_reentrant_grain,
    iter_functions,
    iter_grain_classes,
    lexical_walk,
)

# Dotted call names that block the event loop outright.
BLOCKING_CALLS = {
    "time.sleep": "time.sleep() blocks the event loop; await asyncio.sleep",
    "os.system": "os.system() blocks the event loop",
    "subprocess.run": "subprocess.run() blocks the event loop",
    "subprocess.call": "subprocess.call() blocks the event loop",
    "subprocess.check_call": "subprocess.check_call() blocks the event loop",
    "subprocess.check_output":
        "subprocess.check_output() blocks the event loop",
    "socket.create_connection":
        "sync socket connect blocks the event loop",
    "urllib.request.urlopen": "sync HTTP blocks the event loop",
    "requests.get": "sync HTTP blocks the event loop",
    "requests.post": "sync HTTP blocks the event loop",
    "requests.request": "sync HTTP blocks the event loop",
}


@register
class BlockingInTurn(Rule):
    id = "OTPU002"
    name = "blocking-in-turn"
    severity = "error"
    description = ("time.sleep / sync IO / Future.result() inside an "
                   "async def turn")
    rationale = (
        "A grain turn shares the silo's single event loop with every "
        "other activation: one synchronous sleep, file read, or "
        ".result() wait stalls the WHOLE silo — probe responses "
        "included, which gets healthy silos voted dead under load. "
        "Await the async form, or move the blocking work to "
        "loop.run_in_executor.")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for qualname, fn in iter_functions(ctx.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in lexical_walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name in BLOCKING_CALLS:
                    yield ctx.finding(self, node,
                                      f"{BLOCKING_CALLS[name]} in async "
                                      "turn", qualname)
                elif name == "open":
                    yield ctx.finding(
                        self, node,
                        "sync file IO (open) in async turn; use a thread "
                        "executor or accept the stall explicitly", qualname)
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "result" and not node.args \
                        and not node.keywords:
                    yield ctx.finding(
                        self, node,
                        "synchronous .result() in async turn blocks the "
                        "event loop unless the future is already done",
                        qualname)


class _InterleaveScan(ast.NodeVisitor):
    """Lexical-order event scan of one async grain method: attribute
    writes on ``self``, awaits, attribute reads on ``self``. Writes that
    an await has 'crossed' are hazardous to read until rewritten."""

    def __init__(self, rule: Rule, ctx: FileContext, qualname: str,
                 self_name: str):
        self.rule = rule
        self.ctx = ctx
        self.qualname = qualname
        self.self_name = self_name
        self.written: set[str] = set()
        self.crossed: set[str] = set()
        self.flagged: set[str] = set()
        self.findings: list[Finding] = []

    # -- helpers ---------------------------------------------------------
    def _is_self_attr(self, node: ast.AST) -> "str | None":
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == self.self_name:
            return node.attr
        return None

    def _write(self, attr: str) -> None:
        self.written.add(attr)
        self.crossed.discard(attr)

    def _read(self, node: ast.Attribute, attr: str) -> None:
        if attr in self.crossed and attr not in self.flagged:
            self.flagged.add(attr)
            self.findings.append(self.ctx.finding(
                self.rule, node,
                f"grain attribute '{attr}' written before an await and "
                "read after it in a non-reentrant grain method; an "
                "interleaved turn may have changed it — re-validate or "
                "move the await", self.qualname))

    # -- visitors (source order) ----------------------------------------
    def visit_If(self, node: ast.If) -> None:
        """Branch-aware: the else branch must not observe the then
        branch's write/await sequence (they are mutually exclusive).
        After the if, the union of branch states holds — a read then is
        hazardous if EITHER branch wrote-and-awaited."""
        self.visit(node.test)
        snap = (set(self.written), set(self.crossed))
        for s in node.body:
            self.visit(s)
        then_state = (self.written, self.crossed)
        self.written, self.crossed = set(snap[0]), set(snap[1])
        for s in node.orelse:
            self.visit(s)
        self.written |= then_state[0]
        self.crossed |= then_state[1]

    def visit_Await(self, node: ast.Await) -> None:
        self.generic_visit(node)        # reads inside the awaited expr
        self.crossed |= self.written

    def _write_target(self, t: ast.expr) -> None:
        """Register writes for one assignment target, unpacking
        tuple/list/starred targets (``self.a, self.b = ...``)."""
        attr = self._is_self_attr(t)
        if attr is not None:
            self._write(attr)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._write_target(el)
        elif isinstance(t, ast.Starred):
            self._write_target(t.value)
        else:
            self.visit(t)

    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)          # RHS reads happen first
        for t in node.targets:
            self._write_target(t)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self.visit(node.value)
        attr = self._is_self_attr(node.target)
        if attr is not None:
            self._write(attr)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.visit(node.value)
        attr = self._is_self_attr(node.target)
        if attr is not None:
            # read-modify-write: the read half observes the stale value
            self._read(node.target, attr)
            self._write(attr)
        else:
            self.visit(node.target)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = self._is_self_attr(node)
        if attr is not None and isinstance(node.ctx, ast.Load):
            self._read(node, attr)
        self.generic_visit(node)

    # nested defs/lambdas execute later — out of turn order
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        pass

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass


@register
class InterleavingHazard(Rule):
    id = "OTPU003"
    name = "interleaving-hazard"
    severity = "warning"
    description = ("grain attribute written before and read after an "
                   "await in a non-reentrant grain method")
    rationale = (
        "Non-reentrant grains still interleave at awaits: "
        "always-interleave methods, call-chain reentrancy, read-only "
        "interleaving, and timer turns can all run between an await "
        "and the statement after it. Instance state written before "
        "the await may be stale when read after — re-validate it, or "
        "move the await so the read-modify-write is atomic within "
        "one turn segment.")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for cls_qual, cls in iter_grain_classes(ctx.tree):
            if is_reentrant_grain(cls):
                continue
            for stmt in cls.body:
                if not isinstance(stmt, ast.AsyncFunctionDef):
                    continue
                if "staticmethod" in decorator_names(stmt) or \
                        not stmt.args.args:
                    continue
                scan = _InterleaveScan(self, ctx,
                                       f"{cls_qual}.{stmt.name}",
                                       stmt.args.args[0].arg)
                for s in stmt.body:
                    scan.visit(s)
                yield from scan.findings
