"""Rule modules — importing this package registers every rule."""

from . import concurrency, interfaces, pool, state, traced, turns  # noqa: F401

__all__ = ["concurrency", "interfaces", "pool", "state", "traced",
           "turns"]
