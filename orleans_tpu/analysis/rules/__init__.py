"""Rule modules — importing this package registers every rule."""

from . import pool, state, traced, turns  # noqa: F401

__all__ = ["pool", "state", "traced", "turns"]
