"""Rule modules — importing this package registers every rule."""

from . import (  # noqa: F401
    concurrency, interfaces, pool, rings, state, traced, turns,
)

__all__ = ["concurrency", "interfaces", "pool", "rings", "state",
           "traced", "turns"]
