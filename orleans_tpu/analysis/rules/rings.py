"""OTPU010 — shm-ring discipline for the cross-process tier (PR 18).

``runtime/multiproc.py`` stretches the multiloop SpscRing contract over
a process boundary: one shared-memory segment per direction, cumulative
u64 counters with exactly ONE writing side each, opaque-bytes records,
and a drain-before-unlink shutdown so ``pushed == drained`` holds when
the segment disappears. None of that is testable exhaustively — a
wrong-side counter store corrupts the backlog signal only under racing
load, and an object reference pushed across a segment deserializes as
garbage only in the *other* process. This rule audits the discipline
statically, in four checks:

**A — single-writer counters.** A ring counter may only be stored by
its owning side. Two shapes are recognised: header-offset stores
(``self._store(_OFF_READ, ...)`` / ``pack_into(.., _OFF_PUSHED, ..)``
against the shared ``_OFF_*`` layout constants) and cumulative counter
attributes (``self.pushed_* `` / ``self.drained_*`` on a class that
maintains both families — the SpscRing shape). The writing method's
side comes from its name (``*push*`` = producer; ``*pop*``/``*drain*``/
``*discard*`` = consumer); ``__init__`` is exempt (construction
precedes concurrency). A store from the opposite side OR from a method
on neither side is flagged — a "reset" helper that zeroes a cumulative
counter is exactly the race the layout comment forbids.

**B — only bytes cross a segment.** The payload handed to ``push`` on
an shm-owning ring (or to the native ``shm_push(buf, cap, payload,
n)``) must provably be bytes: a bytes literal, a serializer call
(``dumps``/``pack``/``to_bytes``/``encode``/...), a ``bytes``-annotated
parameter, or a local whose every assignment is one of those. A
container literal, str, or constructor result is a Python object
reference — meaningless in the consumer's address space — and is
flagged. Unprovable payloads are skipped, not flagged.

**C — drain-before-unlink.** Any function that unlinks a shared-memory
segment (an ``unlink`` call whose receiver chain mentions ``shm``) must
take a final drain sweep first (an earlier call whose name contains
``drain`` or ``pop``), so every pushed record is accounted before the
backing pages go away. Functions that themselves CREATE the segment
(``SharedMemory(create=True)`` rollback paths) are exempt.

**D — dual-affinity container mutation.** A list/dict/set attribute
mutated structurally (pop/remove/clear/subscript-store/...) from
worker-thread context while the main loop also touches it needs a lock
or fence; flagged when the worker-side mutation is bare. Plain appends
from the worker are the sanctioned stamp-and-replay feed (appends are
not writes — the OTPU007 contract), and ``deque`` attributes are the
sanctioned GIL-atomic hand-off (the SpscRing ``_items`` discipline), so
neither is flagged; shm-owning ring classes are covered by check A
instead. This check needs the linked program (worker affinity is a
phase-2 fixpoint) and is skipped under ``--intra-only``.

PR 18's free-threading direction is the motivation: every one of these
is a latent ``nogil`` crash that the GIL currently papers over.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..model import FileContext, Finding, Rule, register
from ..summaries import dotted_name
from .common import iter_functions

# header-offset layout constants (multiproc.ShmRing and hotwire.c agree
# on these by name)
_PRODUCER_OFFS = {"_OFF_WRITE", "_OFF_PUSHED"}
_CONSUMER_OFFS = {"_OFF_READ", "_OFF_DRAINED"}
_PRODUCER_HINTS = ("push",)
_CONSUMER_HINTS = ("pop", "drain", "discard")
_STORE_NAMES = {"_store", "pack_into"}

_SERIALIZERS = {"dumps", "pack", "to_bytes", "tobytes", "encode",
                "serialize", "bytes", "bytearray", "memoryview"}

# container mutators; append/appendleft are the sanctioned worker-side
# stamp feed and are judged separately
_MUTATORS = {"append", "appendleft", "extend", "insert", "add", "pop",
             "popleft", "popitem", "remove", "discard", "clear",
             "update", "setdefault"}
_FEED_ONLY = {"append", "appendleft"}


def _method_side(name: str) -> str | None:
    """'producer' | 'consumer' | None from a method's short name."""
    low = name.lower()
    prod = any(h in low for h in _PRODUCER_HINTS)
    cons = any(h in low for h in _CONSUMER_HINTS)
    if prod and not cons:
        return "producer"
    if cons and not prod:
        return "consumer"
    return None


def _counter_owner(attr: str) -> str | None:
    if attr.startswith("pushed"):
        return "producer"
    if attr.startswith("drained"):
        return "consumer"
    return None


def _chain(call: ast.Call) -> tuple:
    dn = dotted_name(call.func)
    return tuple(dn.split(".")) if dn else ()


def _bytes_params(fn) -> set:
    out = set()
    a = fn.args
    for p in (*a.posonlyargs, *a.args, *a.kwonlyargs):
        ann = p.annotation
        if isinstance(ann, ast.Name) and ann.id == "bytes":
            out.add(p.arg)
        elif isinstance(ann, ast.Constant) and ann.value == "bytes":
            out.add(p.arg)
    return out


def _local_assigns(fn) -> dict:
    """name → [every value expr assigned to that bare name]."""
    out: dict = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            out.setdefault(node.targets[0].id, []).append(node.value)
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and \
                node.value is not None:
            out.setdefault(node.target.id, []).append(node.value)
    return out


def _payload_verdict(expr, assigns: dict, bytes_params: set,
                     depth: int = 0) -> str:
    """'ok' (provably bytes) | 'bad' (provably an object ref) |
    'unknown' (skipped — the check only convicts on proof)."""
    if depth > 3:
        return "unknown"
    if isinstance(expr, ast.Constant):
        return "ok" if isinstance(expr.value, bytes) else "bad"
    if isinstance(expr, (ast.List, ast.Tuple, ast.Set, ast.Dict,
                         ast.ListComp, ast.SetComp, ast.DictComp,
                         ast.JoinedStr)):
        return "bad"
    if isinstance(expr, ast.Call):
        last = _chain(expr)[-1:] or ("",)
        if last[0] in _SERIALIZERS:
            return "ok"
        if isinstance(expr.func, ast.Name) and expr.func.id[:1].isupper():
            return "bad"            # constructor by convention
        return "unknown"
    if isinstance(expr, ast.Name):
        if expr.id in bytes_params:
            return "ok"
        vals = assigns.get(expr.id)
        if not vals:
            return "unknown"
        verdicts = {_payload_verdict(v, assigns, bytes_params, depth + 1)
                    for v in vals}
        if "bad" in verdicts:
            return "bad"
        if verdicts == {"ok"}:
            return "ok"
        return "unknown"
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        l = _payload_verdict(expr.left, assigns, bytes_params, depth + 1)
        r = _payload_verdict(expr.right, assigns, bytes_params, depth + 1)
        if "bad" in (l, r):
            return "bad"
        return "ok" if (l, r) == ("ok", "ok") else "unknown"
    if isinstance(expr, ast.IfExp):
        b = _payload_verdict(expr.body, assigns, bytes_params, depth + 1)
        o = _payload_verdict(expr.orelse, assigns, bytes_params,
                             depth + 1)
        if "bad" in (b, o):
            return "bad"
        return "ok" if (b, o) == ("ok", "ok") else "unknown"
    return "unknown"


def _lockish(expr) -> bool:
    """A with-item that provides mutual exclusion: anything whose
    dotted name mentions lock or the tick fence."""
    if isinstance(expr, ast.Call):
        expr = expr.func
    dn = dotted_name(expr).lower()
    return any("lock" in seg or "fence" in seg for seg in dn.split("."))


def _self_attr_of(node) -> str | None:
    """'self.X' expression → 'X'."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


@register
class RingDiscipline(Rule):
    id = "OTPU010"
    name = "shm-ring-discipline"
    severity = "error"
    description = ("cross-process SPSC ring invariant broken: wrong-"
                   "side counter store, non-bytes payload across an "
                   "shm segment, unlink without a final drain, or an "
                   "unlocked dual-affinity container mutation")
    rationale = (
        "The shm rings interoperate with a native producer/consumer on "
        "a bare byte layout: each cumulative counter has exactly one "
        "writing side (a wrong-side store is a lost-update race that "
        "corrupts the backlog/backpressure signal), payloads must be "
        "bytes (an object reference is meaningless in the peer "
        "process), and segments must be drained before unlink so "
        "pushed == drained holds at teardown. Off-loop structural "
        "mutation of a shared list/dict without a lock is the same "
        "bug one tier down — all of these are latent nogil crashes "
        "the GIL currently hides.")

    # ---- A: header-offset counter stores ----------------------------
    def _check_offsets(self, ctx, qual, fn) -> Iterator[Finding]:
        side = _method_side(qual.rsplit(".", 1)[-1])
        if qual.rsplit(".", 1)[-1] == "__init__":
            return
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            ch = _chain(node)
            if not ch or ch[-1] not in _STORE_NAMES:
                continue
            for arg in node.args:
                if not isinstance(arg, ast.Name):
                    continue
                owner = "producer" if arg.id in _PRODUCER_OFFS else \
                    "consumer" if arg.id in _CONSUMER_OFFS else None
                if owner is None or owner == side:
                    continue
                where = f"the {side} side" if side else \
                    "a method on neither ring side"
                yield ctx.finding(
                    self, node,
                    f"{owner}-owned ring counter '{arg.id}' stored from "
                    f"{where}; only the owning side may write a "
                    "cumulative counter (single-writer SPSC contract)",
                    qual)

    # ---- A: cumulative counter attributes ---------------------------
    def _check_counter_attrs(self, ctx, tree) -> Iterator[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            # (method, counter attr, owner, anchor) for every mutation
            muts = []
            for meth in node.body:
                if not isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                for sub in ast.walk(meth):
                    if isinstance(sub, ast.AugAssign):
                        targets = [sub.target]
                    elif isinstance(sub, ast.Assign):
                        targets = sub.targets
                    else:
                        continue
                    for t in targets:
                        attr = _self_attr_of(t)
                        owner = _counter_owner(attr) if attr else None
                        if owner is not None:
                            muts.append((meth.name, attr, owner, sub))
            owners = {m[2] for m in muts}
            if owners != {"producer", "consumer"}:
                continue                # not a two-sided ring class
            for meth_name, attr, owner, anchor in muts:
                if meth_name == "__init__":
                    continue
                side = _method_side(meth_name)
                if side == owner:
                    continue
                where = f"the {side}-side method '{meth_name}'" \
                    if side else f"'{meth_name}', a method on neither " \
                    "ring side"
                yield ctx.finding(
                    self, anchor,
                    f"{owner}-owned cumulative counter 'self.{attr}' "
                    f"written from {where}; only the owning side may "
                    "write it (single-writer SPSC contract)",
                    f"{node.name}.{meth_name}")

    # ---- B: bytes-only payloads -------------------------------------
    def _shm_receiver(self, program, ms, qual, ch) -> bool:
        if len(ch) < 2:
            return False
        if ch[:-1] == ("self",):
            cls = program.enclosing_class(ms, qual)
        else:
            cls = program.receiver_class(ms, qual, ch[:-1])
        if cls is None:
            return False
        hit = program.class_index.get(cls)
        return hit is not None and hit[1].shm_owner

    def _check_payloads(self, ctx, program, ms, qual,
                        fn) -> Iterator[Finding]:
        assigns = _local_assigns(fn)
        bparams = _bytes_params(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            ch = _chain(node)
            if not ch:
                continue
            payload = None
            if ch[-1] == "shm_push":
                # native: shm_push(buf, capacity, payload, n_msgs)
                if len(node.args) >= 3:
                    payload = node.args[2]
            elif ch[-1] in ("push", "_push_py") and \
                    self._shm_receiver(program, ms, qual, ch):
                if node.args:
                    payload = node.args[0]
            if payload is None:
                for kw in node.keywords:
                    if kw.arg == "payload":
                        payload = kw.value
            if payload is None:
                continue
            if _payload_verdict(payload, assigns, bparams) == "bad":
                yield ctx.finding(
                    self, node,
                    "non-bytes payload crosses the shm segment via "
                    f"'{'.'.join(ch)}'; only bytes/struct-packed "
                    "records are meaningful in the peer process — "
                    "serialize first (pickle.dumps/struct.pack)", qual)

    # ---- C: drain-before-unlink -------------------------------------
    def _check_unlink(self, ctx, qual, fn) -> Iterator[Finding]:
        unlinks, drains, creates = [], [], False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            ch = _chain(node)
            if not ch:
                continue
            if ch[-1] == "unlink" and any("shm" in s for s in ch[:-1]):
                unlinks.append(node)
            elif "drain" in ch[-1] or "pop" in ch[-1]:
                drains.append(node.lineno)
            elif ch[-1] == "SharedMemory":
                creates = True
        if creates:
            return                      # creation-rollback path
        for node in unlinks:
            if not any(ln < node.lineno for ln in drains):
                yield ctx.finding(
                    self, node,
                    "shm segment unlinked without a prior drain sweep "
                    "in this function; every shutdown path must drain "
                    "the ring first so pushed == drained when the "
                    "backing pages go away", qual)

    # ---- D: dual-affinity container mutation ------------------------
    def _collect_mutations(self, fn, attrs: set) -> list:
        """[(attr, structural, locked, anchor)] for mutations of
        ``self.<attr>`` with lexical lock/fence tracking."""
        out = []

        def visit(node, locked):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda,
                                      ast.ClassDef)):
                    continue
                sub_locked = locked
                if isinstance(child, (ast.With, ast.AsyncWith)) and \
                        any(_lockish(i.context_expr)
                            for i in child.items):
                    sub_locked = True
                if isinstance(child, ast.Call) and \
                        isinstance(child.func, ast.Attribute) and \
                        child.func.attr in _MUTATORS:
                    attr = _self_attr_of(child.func.value)
                    if attr in attrs:
                        out.append((attr,
                                    child.func.attr not in _FEED_ONLY,
                                    locked, child))
                targets = []
                if isinstance(child, (ast.Assign, ast.AugAssign)):
                    targets = child.targets \
                        if isinstance(child, ast.Assign) \
                        else [child.target]
                elif isinstance(child, ast.Delete):
                    targets = child.targets
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        attr = _self_attr_of(t.value)
                        if attr in attrs:
                            out.append((attr, True, locked, child))
                visit(child, sub_locked)

        visit(fn, False)
        return out

    def _check_dual_affinity(self, ctx, program, ms,
                             tree) -> Iterator[Finding]:
        # attr universe per class: plain list/dict/set attrs on classes
        # that are neither shm rings (check A's beat) nor deque-based
        watched = {}
        for cname, info in ms.classes.items():
            if info.shm_owner:
                continue
            attrs = {a for a, kind in info.container_attrs.items()
                     if kind != "deque"}
            if attrs:
                watched[cname] = attrs
        if not watched:
            return
        # mutation sites per (class, attr), split by affinity; "mixed"
        # functions run under both
        sites: dict = {}
        for qual, fn in iter_functions(tree):
            cls = program.enclosing_class(ms, qual)
            if cls not in watched:
                continue
            kind = program.worker_context((ms.module_key, qual))
            for attr, structural, locked, anchor in \
                    self._collect_mutations(fn, watched[cls]):
                rec = sites.setdefault((cls, attr), {
                    "worker": [], "main": False})
                if kind in ("seed", "only", "mixed"):
                    rec["worker"].append(
                        (structural, locked, anchor, qual,
                         program.worker.get((ms.module_key, qual),
                                            "mixed context")))
                if kind is None or kind == "mixed":
                    rec["main"] = True
        for (cls, attr), rec in sorted(
                sites.items(), key=lambda kv: kv[0]):
            if not rec["main"]:
                continue                # single affinity: no race
            for structural, locked, anchor, qual, reason in \
                    rec["worker"]:
                if not structural or locked:
                    continue            # appends = stamp feed; locked ok
                yield ctx.finding(
                    self, anchor,
                    f"unlocked structural mutation of 'self.{attr}' "
                    f"from worker context ({reason}) while the main "
                    "loop also touches it; guard with a lock/fence or "
                    "restrict the worker side to appends "
                    "(stamp-and-replay)", qual)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        program = ctx.program
        ms = ctx.module
        if program is None or ms is None:
            return                      # phase-2 rule: needs the link
        yield from self._check_counter_attrs(ctx, ctx.tree)
        for qual, fn in iter_functions(ctx.tree):
            yield from self._check_offsets(ctx, qual, fn)
            yield from self._check_payloads(ctx, program, ms, qual, fn)
            yield from self._check_unlink(ctx, qual, fn)
        yield from self._check_dual_affinity(ctx, program, ms, ctx.tree)
