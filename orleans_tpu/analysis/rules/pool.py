"""OTPU001 — pool discipline for freelist-recycled objects.

PR 3 introduced freelists for ``Message`` (``core.message.recycle_message``)
and ``CallbackData`` (``runtime_client._recycle_callback``) plus the
hot-lane running marker (``hotlane._release_marker``). A released shell may
be re-acquired and re-initialized by any later allocation on the event
loop, so touching a local variable after passing it to a releaser is a
use-after-free with Python characteristics: no crash, just another call's
fields. This rule runs a small branch-aware dataflow over each function
that calls a releaser and reports

* any read of a name after it was released on every path reaching the
  read, and
* a second release of an already-released name along one path.

Rebinding (``x = ...``) or ``del x`` clears the released state. The
analysis is intra-procedural and ignores aliases — the cross-function
dataflow upgrade is a ROADMAP follow-on.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..model import FileContext, Finding, Rule, register
from .common import iter_functions

RELEASERS = {
    "recycle_message", "_recycle_callback", "recycle_callback",
    "_release_marker", "release_marker",
}

_TERMINATED = None  # sentinel state for paths that return/raise/break


def _walk_shallow(root: ast.AST) -> Iterator[ast.AST]:
    """Walk without entering nested def/lambda/class bodies — code there
    does not execute at this lexical position."""
    stack: list[ast.AST] = [root]
    while stack:
        node = stack.pop()
        if node is not root and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.Lambda, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _release_calls(stmt: ast.stmt) -> list[tuple[ast.Call, str]]:
    """(call, released-name) for every releaser call in the statement."""
    out = []
    for node in _walk_shallow(stmt):
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else \
                fn.id if isinstance(fn, ast.Name) else ""
            if name in RELEASERS and node.args and \
                    isinstance(node.args[0], ast.Name):
                out.append((node, node.args[0].id))
    return out


class _FuncAnalysis:
    def __init__(self, rule: "PoolDiscipline", ctx: FileContext,
                 qualname: str):
        self.rule = rule
        self.ctx = ctx
        self.qualname = qualname
        self.findings: list[Finding] = []
        self.reported: set[tuple[str, int]] = set()

    # -- state: dict name -> line of the release ------------------------
    def run(self, body: list[ast.stmt]) -> None:
        self.exec_block(body, {})

    def exec_block(self, stmts: list[ast.stmt], state: "dict | None"):
        for stmt in stmts:
            if state is _TERMINATED:
                return _TERMINATED
            state = self.exec_stmt(stmt, state)
        return state

    def _emit(self, node: ast.AST, name: str, message: str) -> None:
        key = (name, getattr(node, "lineno", 0))
        if key not in self.reported:
            self.reported.add(key)
            self.findings.append(self.ctx.finding(
                self.rule, node, message, self.qualname))

    def _scan_uses(self, stmt: ast.stmt, state: dict,
                   skip: set[int]) -> None:
        """Report loads of released names anywhere in the statement,
        skipping the releaser-arg Name nodes (handled as events) and any
        nested def/lambda bodies (executed later, maybe never)."""
        for node in _walk_shallow(stmt):
            if isinstance(node, ast.Name) and id(node) not in skip and \
                    isinstance(node.ctx, ast.Load) and node.id in state:
                self._emit(node, node.id,
                           f"pooled '{node.id}' used after release")

    def _apply_simple(self, stmt: ast.stmt, state: dict) -> dict:
        """Uses → releases → rebinds, in that order, for one statement."""
        releases = _release_calls(stmt)
        skip = {id(call.args[0]) for call, _ in releases}
        self._scan_uses(stmt, state, skip)
        for call, name in releases:
            if name in state:
                self._emit(call, name,
                           f"pooled '{name}' released twice along one path")
            else:
                state[name] = call.lineno
        for node in _walk_shallow(stmt):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)):
                state.pop(node.id, None)
        return state

    @staticmethod
    def _merge(states: list) -> "dict | None":
        live = [s for s in states if s is not _TERMINATED]
        if not live:
            return _TERMINATED
        merged = dict(live[0])
        for s in live[1:]:
            merged = {k: min(v, s[k]) for k, v in merged.items() if k in s}
        return merged

    def exec_stmt(self, stmt: ast.stmt, state: dict):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # the body runs later (analyzed as its own function); only the
            # binding of the name happens here
            state.pop(stmt.name, None)
            return state
        if isinstance(stmt, (ast.Return, ast.Raise)):
            self._apply_simple(stmt, state)
            return _TERMINATED
        if isinstance(stmt, (ast.Break, ast.Continue)):
            return _TERMINATED
        if isinstance(stmt, ast.If):
            self._apply_simple(ast.Expr(stmt.test), state)
            s_body = self.exec_block(stmt.body, dict(state))
            s_else = self.exec_block(stmt.orelse, dict(state))
            return self._merge([s_body, s_else])
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            if isinstance(stmt, ast.While):
                self._apply_simple(ast.Expr(stmt.test), state)
            else:
                self._apply_simple(ast.Expr(stmt.iter), state)
                for node in ast.walk(stmt.target):
                    if isinstance(node, ast.Name):
                        state.pop(node.id, None)
            # one symbolic pass through the body catches straight-line
            # release→use inside an iteration; loop-carried state (release
            # in iteration N, use in N+1) is a known gap (ROADMAP)
            self.exec_block(stmt.body, dict(state))
            self.exec_block(stmt.orelse, dict(state))
            return state
        if isinstance(stmt, (ast.Try, getattr(ast, "TryStar", ast.Try))):
            s_body = self.exec_block(stmt.body, dict(state))
            if s_body is not _TERMINATED and stmt.orelse:
                s_body = self.exec_block(stmt.orelse, s_body)
            # handlers run from the PRE-try state: the exception may have
            # fired before any release in the body executed
            ends = [s_body]
            for handler in stmt.handlers:
                ends.append(self.exec_block(handler.body, dict(state)))
            merged = self._merge(ends)
            fin_in = merged if merged is not _TERMINATED else dict(state)
            fin_out = self.exec_block(stmt.finalbody, dict(fin_in))
            if merged is _TERMINATED or fin_out is _TERMINATED:
                return _TERMINATED
            return fin_out
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._apply_simple(ast.Expr(item.context_expr), state)
                if item.optional_vars is not None:
                    for node in ast.walk(item.optional_vars):
                        if isinstance(node, ast.Name):
                            state.pop(node.id, None)
            return self.exec_block(stmt.body, state)
        match_cls = getattr(ast, "Match", None)
        if match_cls is not None and isinstance(stmt, match_cls):
            self._apply_simple(ast.Expr(stmt.subject), state)
            ends = [self.exec_block(case.body, dict(state))
                    for case in stmt.cases]
            ends.append(dict(state))  # no case may match
            return self._merge(ends)
        return self._apply_simple(stmt, state)


@register
class PoolDiscipline(Rule):
    id = "OTPU001"
    name = "pool-discipline"
    severity = "error"
    description = ("pooled Message/CallbackData/marker used after "
                   "release, or released twice along one path")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for qualname, fn in iter_functions(ctx.tree):
            if not any(_release_calls(s) for s in fn.body):
                continue
            analysis = _FuncAnalysis(self, ctx, qualname)
            analysis.run(fn.body)
            yield from analysis.findings
