"""OTPU001 — pool discipline for freelist-recycled objects.

PR 3 introduced freelists for ``Message`` (``core.message.recycle_message``)
and ``CallbackData`` (``runtime_client._recycle_callback``) plus the
hot-lane running marker (``hotlane._release_marker``). A released shell may
be re-acquired and re-initialized by any later allocation on the event
loop, so touching a local variable after passing it to a releaser is a
use-after-free with Python characteristics: no crash, just another call's
fields. The rule runs the shared release dataflow
(``analysis.summaries.ReleaseWalker``) over each candidate function and
reports

* any read of a name after it was released on every path reaching the
  read,
* a second release of an already-released name along one path.

Since PR 14 the dataflow is **cross-function, alias-aware, and
loop-carried**: a helper whose summary definitely releases a parameter
poisons the caller's argument at the call site (the Infer-style
compositional propagation, resolved module-locally plus through explicit
imports); ``y = x`` (and ``y = helper(x)`` when the helper returns its
argument) makes ``y`` an alias whose release poisons the group; and loop
bodies run twice with the back-edge state merged in, so a release in
iteration N reaches a use in iteration N+1. Rebinding (``x = ...``) or
``del x`` still clears the released state.

Since the context-sensitivity upgrade the alias flow also crosses
container and attribute boundaries: ``self._pending = m`` makes the
attribute an alias of ``m``, ``batch.append(m)`` records membership so
an item-release of the batch (``recycle_messages`` or a callee whose
summary releases its container elements) poisons ``m``, and release
depth is closed CROSS-module at link time via the Program's release
overlay — a wrapper around an imported releaser poisons its callers'
arguments even through multiple modules. The legacy intra-procedural
configuration (no call-site propagation) stays available via the CLI's
``--intra-only``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..model import FileContext, Finding, Rule, register
from ..summaries import (
    ITEM_RELEASERS,
    RELEASERS,
    ReleaseWalker,
    _arg_cell_name,
    _call_alias,
    _call_releases,
    _call_releases_items,
)
from .common import iter_functions


def _direct_releases(call: ast.Call) -> list[str]:
    fn = call.func
    name = fn.attr if isinstance(fn, ast.Attribute) else \
        fn.id if isinstance(fn, ast.Name) else ""
    if name in RELEASERS and call.args:
        nm = _arg_cell_name(call.args[0])
        if nm is not None:
            return [nm]
    return []


def _direct_item_releases(call: ast.Call) -> list[str]:
    fn = call.func
    name = fn.attr if isinstance(fn, ast.Attribute) else \
        fn.id if isinstance(fn, ast.Name) else ""
    if name in ITEM_RELEASERS and call.args:
        nm = _arg_cell_name(call.args[0])
        if nm is not None:
            return [nm]
    return []


def _has_releaser_call(fn) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else \
                f.id if isinstance(f, ast.Name) else ""
            if name in RELEASERS or name in ITEM_RELEASERS:
                return True
    return False


def _pos_params(fn) -> list[str]:
    a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args)]


@register
class PoolDiscipline(Rule):
    id = "OTPU001"
    name = "pool-discipline"
    severity = "error"
    description = ("pooled Message/CallbackData/marker used after "
                   "release, or released twice along one path")
    rationale = (
        "Freelist-recycled objects (Message, CallbackData, the hot-lane "
        "running marker) may be re-acquired and re-initialized by ANY "
        "later allocation the moment they are released. Reading one "
        "after release silently observes another request's fields — no "
        "crash, just wrong data on the wire. The analysis is "
        "interprocedural: a helper that definitely recycles its "
        "argument poisons the caller's variable, aliases share the "
        "poison, and loop-carried state catches a release in iteration "
        "N used in iteration N+1.")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        program = ctx.program
        ms = ctx.module
        releasing_short: set[str] = set()
        if ms is not None:
            for q, s in ms.functions.items():
                if s.releases or s.releases_items:
                    releasing_short.add(q.rsplit(".", 1)[-1])
            if program is not None:
                for key, s in program.functions.items():
                    eff = program.release_summary(key)
                    if eff.releases or eff.releases_items:
                        releasing_short.add(key[1].rsplit(".", 1)[-1])

        for qualname, fn in iter_functions(ctx.tree):
            candidate = _has_releaser_call(fn)
            if not candidate and releasing_short:
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call):
                        f = node.func
                        name = f.attr if isinstance(f, ast.Attribute) \
                            else f.id if isinstance(f, ast.Name) else ""
                        if name in releasing_short:
                            candidate = True
                            break
            if not candidate:
                continue

            findings: list[Finding] = []

            def on_use(node, name, line, _q=qualname, _f=findings):
                _f.append(ctx.finding(
                    self, node,
                    f"pooled '{name}' used after release", _q))

            def on_double(node, name, _q=qualname, _f=findings):
                _f.append(ctx.finding(
                    self, node,
                    f"pooled '{name}' released twice along one path",
                    _q))

            if ms is not None:
                extern = program.extern_summary(ms, qualname) \
                    if program is not None else None
                rel = (lambda c, _q=qualname, _e=extern:
                       _call_releases(ms, _q, c, _e))
                alias = (lambda c, _q=qualname, _e=extern:
                         _call_alias(ms, _q, c, _e))
                items = (lambda c, _q=qualname, _e=extern:
                         _call_releases_items(ms, _q, c, _e))
            else:
                rel = _direct_releases
                alias = None
                items = _direct_item_releases

            walker = ReleaseWalker(_pos_params(fn), release_of_call=rel,
                                   alias_of_call=alias, on_use=on_use,
                                   on_double=on_double,
                                   items_release_of_call=items)
            walker.run(fn.body)
            yield from findings
