"""OTPU004 mutable-state-leak and OTPU005 unawaited-grain-call.

OTPU004: in-silo calls pass results by reference after ``copy_result``
isolation — but a grain method that does ``return self._rows`` hands the
caller the grain's OWN container on the direct-interleave and testing
paths, and the copy-isolation layer then shares structure across turns.
Returning internal mutable state by reference breaks the actor isolation
contract; return a copy.

OTPU005: ``ref.method(...)`` on a grain reference returns a coroutine;
dropping it on the floor means the call never happens (Python never
schedules it) — the classic silent-no-op. Either ``await`` it, keep the
handle (``t = ref.m()`` / ``asyncio.ensure_future(...)``), or mark intent
with ``# otpu: ignore[OTPU005]`` for a deliberate drop.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..model import FileContext, Finding, Rule, register
from .common import (
    dotted_name,
    iter_functions,
    iter_grain_classes,
    lexical_walk,
)

MUTABLE_CTORS = {
    "list", "dict", "set", "bytearray", "defaultdict", "deque",
    "Counter", "OrderedDict",
}

GRAIN_REF_PRODUCERS = {"get_grain", "get_ref", "grain_ref"}


def _is_mutable_value(node: ast.expr) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        return dotted_name(node.func).rsplit(".", 1)[-1] in MUTABLE_CTORS
    return False


@register
class MutableStateLeak(Rule):
    id = "OTPU004"
    name = "mutable-state-leak"
    severity = "warning"
    description = ("grain method returns a shared mutable internal "
                   "by reference")
    rationale = (
        "In-silo calls pass results by reference on the hot lane and "
        "direct-interleave paths: returning self._rows hands the "
        "caller the grain's OWN container, and a later turn's "
        "mutation is visible across the actor isolation boundary. "
        "Return a copy (list(...)/dict(...)).")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for cls_qual, cls in iter_grain_classes(ctx.tree):
            mutable_attrs: set[str] = set()
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign) and \
                        _is_mutable_value(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            mutable_attrs.add(t.attr)
                elif isinstance(node, ast.AnnAssign) and \
                        node.value is not None and \
                        _is_mutable_value(node.value) and \
                        isinstance(node.target, ast.Attribute) and \
                        isinstance(node.target.value, ast.Name) and \
                        node.target.value.id == "self":
                    mutable_attrs.add(node.target.attr)
            if not mutable_attrs:
                continue
            for stmt in cls.body:
                if not isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                for node in ast.walk(stmt):
                    if isinstance(node, ast.Return) and \
                            isinstance(node.value, ast.Attribute) and \
                            isinstance(node.value.value, ast.Name) and \
                            node.value.value.id == "self" and \
                            node.value.attr in mutable_attrs:
                        a = node.value.attr
                        yield ctx.finding(
                            self, node,
                            f"returns shared mutable grain state "
                            f"'self.{a}' by reference; return a copy "
                            f"(e.g. list(self.{a}) / dict(self.{a}))",
                            f"{cls_qual}.{stmt.name}")


@register
class UnawaitedGrainCall(Rule):
    id = "OTPU005"
    name = "unawaited-grain-call"
    severity = "error"
    description = ("grain-ref coroutine dropped without await or an "
                   "explicit fire-and-forget marker")
    rationale = (
        "ref.method() returns a coroutine; dropping it on the floor "
        "means Python never schedules it — the call silently does "
        "not happen. Await it, keep the handle, or mark a deliberate "
        "drop with # otpu: ignore[OTPU005]. @one_way methods are "
        "exempt via the typed interface tables: their invoke returns "
        "None by design.")

    def _ref_class(self, ctx: FileContext, call: ast.Call) -> str | None:
        """The grain class a get_grain(...) call names, when the program
        has an interface table for it."""
        if ctx.program is None or not call.args:
            return None
        name = dotted_name(call.args[0]).rsplit(".", 1)[-1]
        return name if name and name in ctx.program.grains else None

    def _is_one_way(self, ctx: FileContext, cls: str | None,
                    method: str) -> bool:
        """A dropped @one_way call is the CORRECT usage (the invoke
        returns None, there is no coroutine to lose) — the typed
        interface table makes that knowable statically."""
        if cls is None or ctx.program is None:
            return False
        gm = ctx.program.grains[cls].methods.get(method)
        return gm is not None and gm.one_way

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for qualname, fn in iter_functions(ctx.tree):
            # which Name-store nodes bind a grain ref (targets of
            # `x = <something>.get_grain(...)` assignments), and the
            # grain class when the call names it literally
            ref_binds: dict[int, str | None] = {}
            for node in lexical_walk(fn):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and \
                        dotted_name(node.value.func).rsplit(".", 1)[-1] \
                        in GRAIN_REF_PRODUCERS:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            ref_binds[id(t)] = self._ref_class(
                                ctx, node.value)
            # single lexical pass: a rebind to anything else KILLS the
            # ref-ness of the name, so `r = get_grain(..); r = conn();
            # r.flush()` is not flagged
            refs: dict[str, str | None] = {}
            for node in lexical_walk(fn):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, (ast.Store, ast.Del)):
                    if id(node) in ref_binds:
                        refs[node.id] = ref_binds[id(node)]
                    else:
                        refs.pop(node.id, None)
                    continue
                if not (isinstance(node, ast.Expr) and
                        isinstance(node.value, ast.Call)):
                    continue
                call = node.value
                if not isinstance(call.func, ast.Attribute):
                    continue
                recv = call.func.value
                cls = None
                dropped = False
                if isinstance(recv, ast.Name) and recv.id in refs:
                    dropped = True
                    cls = refs[recv.id]
                elif isinstance(recv, ast.Call) and \
                        dotted_name(recv.func).rsplit(".", 1)[-1] \
                        in GRAIN_REF_PRODUCERS:
                    dropped = True
                    cls = self._ref_class(ctx, recv)
                if dropped and not self._is_one_way(ctx, cls,
                                                    call.func.attr):
                    yield ctx.finding(
                        self, call,
                        f"grain call '.{call.func.attr}(...)' result "
                        "dropped — the coroutine is never scheduled; "
                        "await it, keep the handle, or mark the drop "
                        "with # otpu: ignore[OTPU005]", qualname)
