"""Shared AST helpers for the OTPU rules."""

from __future__ import annotations

import ast
from typing import Iterator

# shared with the summary engine (which rule modules must not be
# imported BY — rules import common, common imports summaries)
from ..summaries import GRAIN_BASES, dotted_name, func_params

__all__ = [
    "GRAIN_BASES", "dotted_name", "decorator_names", "is_grain_class",
    "is_reentrant_grain", "iter_functions", "iter_grain_classes",
    "func_params", "lexical_walk",
]


def decorator_names(node: ast.ClassDef | ast.FunctionDef |
                    ast.AsyncFunctionDef) -> list[str]:
    """Dotted names of decorators; a decorator-factory call contributes
    its callee's name (``@placement("hash")`` → ``placement``)."""
    out = []
    for d in node.decorator_list:
        if isinstance(d, ast.Call):
            d = d.func
        name = dotted_name(d)
        if name:
            out.append(name)
    return out


def is_grain_class(node: ast.ClassDef) -> bool:
    for base in node.bases:
        last = dotted_name(base).rsplit(".", 1)[-1]
        if last in GRAIN_BASES:
            return True
    return False


def is_reentrant_grain(node: ast.ClassDef) -> bool:
    """``@reentrant`` decorator or a literal ``__orleans_reentrant__ = True``
    in the class body."""
    for name in decorator_names(node):
        if name.rsplit(".", 1)[-1] == "reentrant":
            return True
    for stmt in node.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name) and \
                        t.id == "__orleans_reentrant__":
                    v = stmt.value
                    if isinstance(v, ast.Constant) and v.value:
                        return True
    return False


def iter_functions(tree: ast.AST, qualprefix: str = "") -> Iterator[
        tuple[str, "ast.FunctionDef | ast.AsyncFunctionDef"]]:
    """Yield (qualname, node) for every def/async def, nested included."""
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qn = f"{qualprefix}{node.name}"
            yield qn, node
            yield from iter_functions(node, qn + ".")
        elif isinstance(node, ast.ClassDef):
            yield from iter_functions(node, f"{qualprefix}{node.name}.")


def iter_grain_classes(tree: ast.AST,
                       qualprefix: str = "") -> Iterator[
        tuple[str, ast.ClassDef]]:
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.ClassDef):
            qn = f"{qualprefix}{node.name}"
            if is_grain_class(node):
                yield qn, node
            yield from iter_grain_classes(node, qn + ".")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield from iter_grain_classes(node, f"{qualprefix}{node.name}.")


def lexical_walk(node: ast.AST, *, into_defs: bool = False
                 ) -> Iterator[ast.AST]:
    """Depth-first walk in source order (``ast.walk`` is breadth-first,
    which scrambles before/after-await ordering). By default does NOT
    descend into nested function/class definitions — a nested def's body
    does not execute at its lexical position."""
    for child in ast.iter_child_nodes(node):
        yield child
        if not into_defs and isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef,
                        ast.Lambda, ast.ClassDef)):
            continue
        yield from lexical_walk(child, into_defs=into_defs)
