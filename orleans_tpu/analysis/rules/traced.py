"""OTPU006 — purity of functions handed to jit / shard_map / pjit.

DrJAX-style traced-primitive discipline for the device tier: a function
traced by ``jax.jit``/``shard_map``/``pjit`` runs ONCE at trace time and
is then replayed as a compiled kernel — any host state it captures is
frozen at trace time, and any host state it mutates mutates only during
tracing (then silently never again). In ``dispatch/``, ``ops/`` and
``parallel/`` that means: no reads of ``self.*`` (host runtime objects),
no mutation of captured containers, no wall clock / host RNG.

Scope is limited to those directories by design: host-tier code is free
to close over runtime state.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..model import FileContext, Finding, Rule, register
from .common import dotted_name, func_params, lexical_walk

TRACING_WRAPPERS = {"jit", "pjit", "shard_map", "shard_map_compat"}
DEVICE_DIRS = ("dispatch", "ops", "parallel")

IMPURE_CALLS = {
    "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
    "time.monotonic_ns", "random.random", "random.randint",
    "random.choice", "random.shuffle", "random.uniform",
    "np.random", "numpy.random",
}

MUTATOR_METHODS = {
    "append", "extend", "add", "update", "insert", "remove", "pop",
    "popleft", "appendleft", "setdefault", "clear", "discard",
}


def _wrapper_target(call: ast.Call) -> "ast.expr | None":
    """First positional arg of a tracing-wrapper call, else None.
    Handles ``jax.jit(f)``, ``shard_map_compat(f, mesh=...)``,
    ``partial(jax.jit, ...)`` (returns None — no target yet)."""
    last = dotted_name(call.func).rsplit(".", 1)[-1]
    if last in TRACING_WRAPPERS and call.args:
        return call.args[0]
    return None


def _decorator_traces(dec: ast.expr) -> bool:
    """True for ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)``."""
    if isinstance(dec, ast.Call):
        last = dotted_name(dec.func).rsplit(".", 1)[-1]
        if last in TRACING_WRAPPERS:
            return True
        if last == "partial" and dec.args:
            return dotted_name(dec.args[0]).rsplit(".", 1)[-1] \
                in TRACING_WRAPPERS
        return False
    return dotted_name(dec).rsplit(".", 1)[-1] in TRACING_WRAPPERS


def _in_device_dir(rel_path: str) -> bool:
    parts = rel_path.split("/")
    return any(d in parts for d in DEVICE_DIRS)


@register
class TracedImpurity(Rule):
    id = "OTPU006"
    name = "traced-impurity"
    severity = "warning"
    description = ("jit/shard_map/pjit-traced function captures or "
                   "mutates host runtime state")
    rationale = (
        "A function handed to jit/pjit/shard_map runs ONCE at trace "
        "time: attribute writes, captured-container mutations, and "
        "host clock/RNG reads are baked into the compiled program (or "
        "silently lost), then never re-execute. Kernel specs must be "
        "closure-pure — pass runtime values as traced arguments and "
        "use jax.random with explicit keys.")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _in_device_dir(ctx.rel_path):
            return
        # Scope-aware name resolution: a jit(f) call resolves `f` against
        # the defs of its OWN scope, then outward through the enclosing
        # scope chain — never against a same-named def in an unrelated
        # scope (two classes both defining an inner `local` must not
        # taint each other).
        defs_in_scope: dict[int, dict[str, list]] = {}
        calls_in_scope: dict[int, list] = {}
        parent: dict[int, "int | None"] = {id(ctx.tree): None}
        qualnames: dict[int, str] = {}
        scopes: list = [ctx.tree]

        def collect(scope: ast.AST, prefix: str) -> None:
            table = defs_in_scope.setdefault(id(scope), {})
            calls = calls_in_scope.setdefault(id(scope), [])
            for node in lexical_walk(scope):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    qn = f"{prefix}{node.name}"
                    parent[id(node)] = id(scope)
                    qualnames[id(node)] = qn
                    scopes.append(node)
                    if not isinstance(node, ast.ClassDef):
                        table.setdefault(node.name, []).append(node)
                    collect(node, qn + ".")
                elif isinstance(node, ast.Call):
                    calls.append(node)

        collect(ctx.tree, "")

        traced: list = []           # (node, qualname) — defs or lambdas
        seen: set[int] = set()

        def resolve(name: str, scope_id: "int | None") -> list:
            while scope_id is not None:
                hits = defs_in_scope.get(scope_id, {}).get(name)
                if hits:
                    return hits
                scope_id = parent.get(scope_id)
            return []

        def mark(target: "ast.expr | None", scope_id: int) -> None:
            if target is None:
                return
            if isinstance(target, ast.Lambda):
                if id(target) not in seen:
                    seen.add(id(target))
                    traced.append((target, "<lambda>"))
            elif isinstance(target, ast.Name):
                for d in resolve(target.id, scope_id):
                    if id(d) not in seen:
                        seen.add(id(d))
                        traced.append((d, qualnames[id(d)]))
            elif isinstance(target, ast.Call):
                # jit(shard_map_compat(f, ...)) — unwrap one level
                mark(_wrapper_target(target), scope_id)

        for scope in scopes:
            for call in calls_in_scope.get(id(scope), ()):
                mark(_wrapper_target(call), id(scope))
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and any(_decorator_traces(d)
                            for d in scope.decorator_list) \
                    and id(scope) not in seen:
                seen.add(id(scope))
                traced.append((scope, qualnames[id(scope)]))

        for fn, qualname in traced:
            yield from self._check_traced(ctx, fn, qualname)

    def _check_traced(self, ctx: FileContext, fn, qualname: str
                      ) -> Iterator[Finding]:
        params = func_params(fn)
        stmts = [fn.body] if isinstance(fn, ast.Lambda) else fn.body
        locals_: set[str] = set(params)
        for stmt in stmts:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Store):
                    locals_.add(node.id)
        for stmt in stmts:
            for node in ast.walk(stmt):
                # global/nonlocal escape hatches
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    yield ctx.finding(
                        self, node,
                        "traced function declares "
                        f"{'global' if isinstance(node, ast.Global) else 'nonlocal'}"
                        " — host state mutated during tracing only",
                        qualname)
                # attribute mutation: x.attr = ... / x.attr += ... —
                # objects BUILT inside the traced function are exempt
                # (same rule as the mutator-method check below): mutating
                # a local scratch object replays fine; mutating a
                # captured one happens at trace time only
                elif isinstance(node, (ast.Assign, ast.AugAssign,
                                       ast.AnnAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        if not isinstance(t, ast.Attribute):
                            continue
                        # unwrap to the base name: out[0].tag = ... roots
                        # at `out` (a subscripted local is still local)
                        base = t.value
                        while isinstance(base, (ast.Attribute,
                                                ast.Subscript,
                                                ast.Starred)):
                            base = base.value
                        if not isinstance(base, ast.Name):
                            continue    # temporary (f().attr): no capture
                        root = base.id
                        if root == "self" and "self" not in params:
                            pass        # captured host object
                        elif root in locals_:
                            continue    # local scratch object
                        yield ctx.finding(
                            self, t,
                            f"traced function mutates attribute "
                            f"'{dotted_name(t) or t.attr}' — the write "
                            "happens at trace time only", qualname)
                elif isinstance(node, ast.Call):
                    name = dotted_name(node.func)
                    root = name.split(".", 1)[0] if name else ""
                    if (name in IMPURE_CALLS or
                            (root in ("random",) and root not in locals_)
                            or name.startswith(("np.random.",
                                                "numpy.random."))):
                        yield ctx.finding(
                            self, node,
                            f"nondeterministic host call '{name}' inside "
                            "traced function — evaluated once at trace "
                            "time; use jax.random with an explicit key",
                            qualname)
                    elif isinstance(node.func, ast.Attribute) and \
                            node.func.attr in MUTATOR_METHODS:
                        recv_root = dotted_name(node.func.value)
                        recv_root = recv_root.split(".", 1)[0] \
                            if recv_root else ""
                        if recv_root and recv_root not in locals_:
                            yield ctx.finding(
                                self, node,
                                f"traced function mutates captured host "
                                f"object '{dotted_name(node.func.value)}"
                                f".{node.func.attr}(...)' — the mutation "
                                "runs at trace time only", qualname)
                # reads of self.* capture host runtime objects
                elif isinstance(node, ast.Attribute) and \
                        isinstance(node.ctx, ast.Load) and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id == "self" and "self" not in params:
                    yield ctx.finding(
                        self, node,
                        f"traced function captures host runtime state "
                        f"'self.{node.attr}' — frozen at trace time; "
                        "pass it as a traced argument or hoist to a "
                        "static closure value deliberately", qualname)
