"""OTPU007/OTPU008 — concurrency-shaped invariants from PRs 9-11.

**OTPU007 loop-confinement.** StatsRegistry / Histogram / QueueWaitTrend /
SpanCollector / CallSiteStats are loop-confined by contract: concurrent
``+=`` loses updates, a first-key insert breaks sampler snapshot
iteration mid-walk, and an off-loop trend note corrupts the shed signal
(the PR-9 review rule). The rule computes the worker-context set over
the linked program — ``threading.Thread`` targets, ``Thread``-subclass
``run`` bodies, ``run_in_executor`` callables, callbacks scheduled onto
a shard loop (``asyncio.new_event_loop`` attr), and everything those
call — and flags registry writes reachable from it. The sanctioned
escape is the **stamp-and-replay pattern**: append ``(key, value)``
stamps to a plain list off-loop and replay them loop-side
(``_complete_job`` / ``_drain_entry`` style); appends are not writes, so
the pattern is clean by construction. Two interprocedural refinements
keep the rule honest at boundaries: a write whose receiver is a bare
*parameter* (``decode_frames(buf, stats)``) or that is guarded by a
``sink is None`` branch is judged at each worker-context CALL SITE —
passing the live registry (or a None sink) from worker code is the
finding, injecting None is clean.

Context sensitivity is k=1 per call edge: a helper reached from BOTH a
worker context and the main loop (or declared as an entry point) is
"mixed" — its definite writes are flagged on each unambiguous worker
call edge into it, never at the definition, so the main-loop path needs
no suppression and the worker path cannot hide.

**OTPU008 fence-discipline.** Donated device state — ``tbl.state`` rows,
hit counters — may be mid-donation inside a worker-side kernel dispatch;
touching it without the tick fence can materialize a deleted array or
commit over a concurrent write (PR 9's grow-vs-upload race). Keyed on
the fence attr protocol: classes that assign ``self.fence``/``self._fence``
own donated state; accesses to ``.state``/``.hits`` on such receivers
must be lexically under ``with x.fence`` / ``x._fence`` /
``x.tick_fence():`` OR inside a function whose every known call site is
fence-held (the compositional summary propagation — ``snapshot()``
called only under the engine fence needs no fence of its own).
``__init__`` bodies are exempt (construction is single-threaded).
"""

from __future__ import annotations

from typing import Iterator

from ..model import FileContext, Finding, Rule, register
from ..summaries import REGISTRY_CLASSES, TYPED_WRITES, UNTYPED_WRITES


class _Anchor:
    """Line/col carrier so FileContext.finding works without an AST
    node at hand (summaries store positions, not nodes)."""

    def __init__(self, lineno: int, col: int):
        self.lineno = lineno
        self.col_offset = col - 1


@register
class LoopConfinement(Rule):
    id = "OTPU007"
    name = "loop-confinement"
    severity = "error"
    description = ("loop-confined registry (StatsRegistry/Histogram/"
                   "QueueWaitTrend/SpanCollector/CallSiteStats) written "
                   "from a worker-thread or ingress-shard context")
    rationale = (
        "The observability registries are loop-confined: they are plain "
        "dicts and floats with no locks. A worker-thread write races "
        "the event loop — concurrent '+=' loses updates, a first-key "
        "histogram insert breaks sampler snapshot iteration, an "
        "off-loop QueueWaitTrend note corrupts the load-shed signal. "
        "The sanctioned pattern is stamp-and-replay: collect (key, "
        "value) stamps in a plain list off-loop, replay them loop-side "
        "(engine._complete_job, multiloop._drain_entry). Passing the "
        "live registry into a decode helper from shard code is the "
        "same bug one call deeper, so call sites are checked too.")

    def _typed_ok(self, program, ms, qual, w) -> bool:
        if w.method in UNTYPED_WRITES:
            return True
        if w.method in TYPED_WRITES:
            cls = program.receiver_class(ms, qual, w.recv)
            return cls in REGISTRY_CLASSES
        return False

    @staticmethod
    def _arg_for(callee, edge, pname):
        """('none'|'live'|'missing') — what the call site passes for the
        callee parameter ``pname``."""
        try:
            idx = list(callee.params).index(pname)
        except ValueError:
            idx = None
        if idx is not None:
            if callee.params and callee.params[0] in ("self", "cls") \
                    and len(edge.chain) >= 2:
                idx -= 1
            if 0 <= idx < edge.nargs:
                return "none" if idx in edge.none_args else "live"
        for kw_name, kw_val in edge.kwargs:
            if kw_name == pname:
                return "none" if kw_val is True else "live"
        return "missing"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        program = ctx.program
        ms = ctx.module
        if program is None or ms is None:
            return
        for qual, s in ms.functions.items():
            key = (ms.module_key, qual)
            kind = program.worker_context(key)
            if kind is None:
                continue
            reason = program.worker.get(key)
            if kind == "mixed":
                # k=1 edge context: this helper is ALSO reached from
                # main-loop context (or is a declared entry point), so
                # its body is not unconditionally worker code — the
                # violation is judged on each worker call EDGE into it
                # (emitted from the caller's side below)
                continue
            # -- direct writes in unambiguous worker context ------------
            for w in s.writes:
                if w.recv_is_param is not None:
                    continue            # judged at call sites below
                if w.guard is not None and w.guard in s.params:
                    continue            # stamp-and-replay guard: ditto
                if not self._typed_ok(program, ms, qual, w):
                    continue
                recv = ".".join(w.recv)
                yield ctx.finding(
                    self, _Anchor(w.lineno, w.col),
                    f"loop-confined registry write '{recv}.{w.method}()'"
                    f" in worker-thread context ({reason}); stamp "
                    "off-loop and replay loop-side", qual)
            # -- call edges out of unambiguous worker context -----------
            seen: set = set()
            for e in s.calls:
                ckey = program.resolve_call(ms, qual, e.chain)
                if ckey is None:
                    continue
                callee = program.functions[ckey]
                callee_kind = program.worker_context(ckey)
                for w in callee.writes:
                    is_param_recv = w.recv_is_param is not None
                    has_guard = w.guard is not None and \
                        w.guard in callee.params
                    if not (is_param_recv or has_guard):
                        # a definite write: flagged at the callee's
                        # definition unless the callee is MIXED — then
                        # THIS worker edge is the k=1 context
                        if callee_kind != "mixed":
                            continue
                        if not self._typed_ok(
                                program, program.modules[ckey[0]],
                                ckey[1], w):
                            continue
                        dkey = (ckey, "edge", w.recv, w.method)
                        if dkey in seen:
                            continue
                        seen.add(dkey)
                        yield ctx.finding(
                            self, _Anchor(e.lineno, e.col),
                            f"worker-context call edge into "
                            f"'{ckey[1]}' (which writes "
                            f"'{'.'.join(w.recv)}.{w.method}()'); the "
                            "helper is also reached from main-loop "
                            "context, so the worker edge is the "
                            f"violation ({reason}); stamp off-loop and "
                            "replay loop-side", qual)
                        continue
                    if not self._typed_ok(
                            program, program.modules[ckey[0]],
                            ckey[1], w):
                        continue
                    if has_guard:
                        g = self._arg_for(callee, e, w.guard)
                        if g == "live":
                            continue    # guard non-None: write skipped
                    if is_param_recv:
                        r = self._arg_for(callee, e, w.recv_is_param)
                        if r in ("none", "missing"):
                            continue    # None injected: write skipped
                    dkey = (ckey, w.recv_is_param or w.guard)
                    if dkey in seen:
                        continue
                    seen.add(dkey)
                    what = f"live registry for '{w.recv_is_param}'" \
                        if is_param_recv else \
                        f"a None '{w.guard}' sink"
                    yield ctx.finding(
                        self, _Anchor(e.lineno, e.col),
                        f"passes {what} into '{ckey[1]}' (which then "
                        f"writes '{'.'.join(w.recv)}.{w.method}()') "
                        f"from worker-thread context ({reason}); "
                        "stamp off-loop and replay loop-side", qual)


@register
class FenceDiscipline(Rule):
    id = "OTPU008"
    name = "fence-discipline"
    severity = "error"
    description = ("donated device state (.state/.hits on a "
                   "fence-owning table/engine) touched outside a held "
                   "tick fence")
    rationale = (
        "The off-loop tick worker holds the engine fence for a whole "
        "batch while tbl.state and the staging operands are DONATED to "
        "the kernel — XLA may already have freed the old buffers. "
        "Reading or swapping .state/.hits without the fence can "
        "materialize a deleted array or commit a tree that erases a "
        "concurrent write (the PR-9 grow-racing-upload case). A "
        "function whose every known call site runs under 'with "
        "x.fence'/'x.tick_fence()' is fence-held by summary "
        "propagation and needs no fence of its own; __init__ bodies "
        "are exempt (construction precedes concurrency).")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        program = ctx.program
        ms = ctx.module
        if program is None or ms is None:
            return
        for qual, s in ms.functions.items():
            key = (ms.module_key, qual)
            accesses = program.protected_accesses(ms, s)
            if not accesses:
                continue
            if program.held.get(key, False):
                continue
            witness = program.unfenced_witness(key) or \
                "an unfenced call path exists"
            seen: set = set()
            for p in accesses:
                if p.fenced:
                    continue
                if p.attr in seen:
                    continue            # one finding per attr per fn
                seen.add(p.attr)
                recv = ".".join(p.recv)
                yield ctx.finding(
                    self, _Anchor(p.lineno, p.col),
                    f"donated device state '{recv}.{p.attr}' touched "
                    f"outside the tick fence ({witness})", qual)
