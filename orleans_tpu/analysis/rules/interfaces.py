"""OTPU009 — typed grain-interface checks (the Roslyn
``IncorrectGrainInterface`` analog).

Python grains have no codegen'd interfaces: a method name is a string
until the call fails at runtime — at the callee silo, one network hop
too late. Phase 1 builds per-class interface tables from the grain class
definitions themselves (host tier: public ``async def``s of ``Grain``
subclasses with positional arity, keyword names and ``@one_way``;
device tier: ``@actor_method`` handlers of ``VectorGrain`` subclasses,
inheritance-merged), and this rule checks every site where code commits
to a (class, method) pair statically:

* ``get_grain(Cls, key)`` call shapes (its own 2-3-arg contract), the
  methods invoked on refs assigned from it — existence, positional
  arity, keyword names — and ``await`` of a ``@one_way`` method (which
  returns None, not an awaitable);
* ``call_batch(Cls, "method", ...)`` method-name strings;
* ``map_actors`` / ``reduce_actors`` / ``broadcast_actors`` /
  ``join_when(method=...)`` — the named class must be a device-tier
  grain and the method an ``@actor_method`` handler.

Sites whose class argument is a variable (the runtime plumbing itself)
are skipped — the rule fires only where the class is named literally,
so a finding is always actionable.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..model import FileContext, Finding, Rule, register
from ..summaries import dotted_name
from .common import iter_functions, lexical_walk

_BULK_VECTOR = {"map_actors", "reduce_actors", "reduce_actors_partial",
                "broadcast_actors"}


def _class_arg(node: ast.Call, program):
    """First positional arg as a known grain-class name, else None."""
    if not node.args:
        return None
    name = dotted_name(node.args[0]).rsplit(".", 1)[-1]
    return name if name and name in program.grains else None


def _method_str(node: ast.AST):
    return node.value if isinstance(node, ast.Constant) and \
        isinstance(node.value, str) else None


@register
class GrainInterface(Rule):
    id = "OTPU009"
    name = "grain-interface"
    severity = "error"
    description = ("grain call site disagrees with the class's "
                   "interface table (unknown method, wrong arity, "
                   "awaited one-way, or host grain in a device-tier "
                   "collective)")
    rationale = (
        "Grain method names are strings and refs are late-bound: a "
        "typo'd method, a wrong argument count, or a host-tier class "
        "handed to map_actors fails at the CALLEE silo, one network "
        "hop and one serialization round after the mistake. The "
        "interface tables are built from the grain class definitions "
        "(public async defs / @actor_method handlers, inheritance-"
        "merged), so the same check the Roslyn IncorrectGrainInterface "
        "analyzer performs at compile time happens here at lint time. "
        "Awaiting a @one_way method is flagged too — one-way invokes "
        "return None, so the await raises TypeError at runtime.")

    # -- per-shape checks -----------------------------------------------
    def _check_get_grain(self, ctx, node, qualname):
        cls = _class_arg(node, ctx.program)
        if cls is None:
            return None, []
        if any(isinstance(a, ast.Starred) for a in node.args) or \
                any(kw.arg is None for kw in node.keywords):
            return cls, []              # *args/**kwargs: unknown shape
        out = []
        kw_names = [kw.arg for kw in node.keywords]
        bad_kw = [k for k in kw_names if k not in ("key", "key_ext")]
        missing_key = len(node.args) < 2 and "key" not in kw_names
        if len(node.args) > 3 or bad_kw or missing_key:
            detail = f"got {len(node.args)} positional arg(s)"
            if bad_kw:
                detail += f" and keyword(s) {bad_kw}"
            if missing_key:
                detail += " — 'key' is required"
            out.append(ctx.finding(
                self, node,
                f"get_grain({cls}, ...) takes (grain_class, key, "
                f"key_ext) — {detail}", qualname))
        return cls, out

    def _check_ref_call(self, ctx, node, cls, awaited, qualname):
        meth = node.func.attr
        if meth.startswith("_"):
            return
        tbl = ctx.program.grains[cls]
        gm = tbl.methods.get(meth)
        if gm is None:
            known = ", ".join(sorted(tbl.methods)) or "none"
            yield ctx.finding(
                self, node,
                f"{cls} has no remote method '{meth}' "
                f"(remote methods: {known})", qualname)
            return
        if tbl.kind == "vector":
            return  # handler args ride kwargs dicts — no arity here
        if any(isinstance(a, ast.Starred) for a in node.args) or \
                any(kw.arg is None for kw in node.keywords):
            return  # *args/**kwargs at the call site: unknown arity
        npos = len(node.args)
        kw_names = [kw.arg for kw in node.keywords]
        if npos > (gm.max_pos if gm.max_pos is not None else npos) or \
                npos + len(kw_names) < gm.min_pos:
            want = f"{gm.min_pos}" if gm.max_pos == gm.min_pos else \
                f"{gm.min_pos}-{'*' if gm.max_pos is None else gm.max_pos}"
            yield ctx.finding(
                self, node,
                f"{cls}.{meth} takes {want} argument(s) — call passes "
                f"{npos} positional + {len(kw_names)} keyword",
                qualname)
        elif not gm.has_kwargs:
            for kw in kw_names:
                if kw not in gm.kwonly:
                    yield ctx.finding(
                        self, node,
                        f"{cls}.{meth} has no parameter '{kw}'",
                        qualname)
        if gm.one_way and awaited:
            yield ctx.finding(
                self, node,
                f"{cls}.{meth} is @one_way (returns None) — "
                "awaiting it raises TypeError", qualname)

    def _check_bulk(self, ctx, node, name, qualname):
        program = ctx.program
        cls = _class_arg(node, program)
        if cls is None:
            return
        tbl = program.grains[cls]
        if name == "call_batch":
            meth = _method_str(node.args[1]) if len(node.args) > 1 \
                else None
            if meth is not None and meth not in tbl.methods:
                yield ctx.finding(
                    self, node,
                    f"call_batch: {cls} has no method '{meth}'",
                    qualname)
            return
        # device-tier collectives
        if tbl.kind != "vector":
            yield ctx.finding(
                self, node,
                f"{name} requires a device-tier (VectorGrain) class — "
                f"{cls} is a host-tier grain", qualname)
            return
        meth = None
        if name == "join_when":
            for kw in node.keywords:
                if kw.arg == "method":
                    meth = _method_str(kw.value)
        elif len(node.args) > 1:
            meth = _method_str(node.args[1])
        if meth is not None and meth not in tbl.methods:
            known = ", ".join(sorted(tbl.methods)) or "none"
            yield ctx.finding(
                self, node,
                f"{name}: {cls} has no @actor_method '{meth}' "
                f"(handlers: {known})", qualname)

    # -- driver ----------------------------------------------------------
    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.program is None or not ctx.program.grains:
            return
        for qualname, fn in iter_functions(ctx.tree):
            # which Name-store nodes bind a typed grain ref (same
            # two-pass shape as OTPU005: binding effects apply at the
            # Store node's LEXICAL position, so a rebind to something
            # else kills the ref-ness for the calls after it — and only
            # those)
            ref_binds: dict[int, str] = {}
            for node in lexical_walk(fn):
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name) and \
                        isinstance(node.value, ast.Call) and \
                        isinstance(node.value.func, ast.Attribute) and \
                        node.value.func.attr == "get_grain":
                    cls = _class_arg(node.value, ctx.program)
                    if cls is not None:
                        ref_binds[id(node.targets[0])] = cls
            awaited_calls = {
                id(n.value) for n in lexical_walk(fn)
                if isinstance(n, ast.Await) and
                isinstance(n.value, ast.Call)}
            refs: dict[str, str] = {}   # live name → grain class
            for node in lexical_walk(fn):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, (ast.Store, ast.Del)):
                    if id(node) in ref_binds:
                        refs[node.id] = ref_binds[id(node)]
                    else:
                        refs.pop(node.id, None)
                    continue
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                name = f.attr if isinstance(f, ast.Attribute) else \
                    f.id if isinstance(f, ast.Name) else ""
                if name == "get_grain":
                    cls, findings = self._check_get_grain(
                        ctx, node, qualname)
                    yield from findings
                elif name in _BULK_VECTOR or name in ("call_batch",
                                                      "join_when"):
                    yield from self._check_bulk(ctx, node, name,
                                                qualname)
                # ref method calls: r.meth(...) and chained
                # get_grain(C, k).meth(...)
                if isinstance(f, ast.Attribute):
                    base = f.value
                    cls = None
                    if isinstance(base, ast.Name) and base.id in refs:
                        cls = refs[base.id]
                    elif isinstance(base, ast.Call) and isinstance(
                            base.func, ast.Attribute) and \
                            base.func.attr == "get_grain":
                        cls = _class_arg(base, ctx.program)
                    if cls is not None:
                        yield from self._check_ref_call(
                            ctx, node, cls, id(node) in awaited_calls,
                            qualname)
