"""Phase-1 per-function summaries + the phase-2 program index.

The interprocedural half of the analyzer (the Infer-style compositional
design): each module is summarized ONCE — independently of every other
module, so summaries cache per file keyed on content hash — and a cheap
linking pass stitches the summaries into a :class:`Program` that rules
query at call sites.

Per function the summary records
* **release behavior** — which parameters are definitely handed to a
  freelist releaser on every normal exit (``recycle_message`` and
  friends, directly or transitively through callees), which parameters
  escape into containers/fields, and whether the function returns one of
  its parameters (an alias the caller must keep tracking);
* **thread affinity** — callables spawned as worker entries
  (``threading.Thread(target=...)``, ``Thread`` subclass ``run`` bodies,
  ``run_in_executor`` callables) and callables handed BACK to an event
  loop (``call_soon_threadsafe``/``add_reader``/``create_task``...),
  keyed on the loop object's inferred kind — ``asyncio.new_event_loop``
  assignments are shard/worker loops, ``get_running_loop`` is the main
  loop;
* **fence state** — accesses to donated device state (``.state`` /
  ``.hits`` on a fence-owning receiver) and call edges, each tagged with
  whether a tick fence (``with x.fence``/``x._fence``/``x.tick_fence()``)
  is lexically held;
* **registry writes** — mutating calls on the loop-confined observability
  classes (StatsRegistry/Histogram/QueueWaitTrend/SpanCollector/
  CallSiteStats), each tagged with the parameter that guards it
  (the ``sink is None`` stamp-and-replay idiom) when there is one.

Modules additionally contribute grain interface tables (host-tier
``Grain`` subclasses → public async method arity/one-way; device-tier
``VectorGrain`` subclasses → ``@actor_method`` names) and lightweight
type specs (annotations, constructor assignments, typed attribute
chains) that phase 2 resolves lazily.

Context sensitivity is k=1 per call edge: phase 2 classifies every
worker-tainted function as a seed, worker-only, or MIXED (also reached
from main-loop context or declared as a runtime entry point), and the
loop-confinement rule judges mixed helpers on the worker call edge
instead of at the definition. Aliases flow through 2-chain attributes
(``self._pending``) and container membership (``batch.append(m)``), and
cross-module release depth closes over a link-time overlay (phase 2
never mutates the cached summaries — re-summarizing an edited module is
enough to re-judge every caller into it). Zero-call-site entry points
(``ctl_*``, timer/reminder callbacks, loop-scheduled and ring-drain
callbacks) get declared contexts from ``entrypoints.py``.

Known, deliberate imprecision (ROADMAP): calling contexts are depth-1
(k>1 chains collapse); bare-name call resolution is module-scoped (plus
explicit imports).
"""

from __future__ import annotations

import ast
import hashlib
import itertools
from dataclasses import dataclass, field, replace
from typing import Iterable

from .entrypoints import entry_label_for_name, entry_label_for_sched

__all__ = [
    "CallEdge", "FunctionSummary", "GrainMethod", "GrainTable",
    "GRAIN_BASES", "ModuleSummary", "Program", "ReleaseWalker",
    "build_program", "dotted_name", "func_params", "module_summary",
    "RELEASERS",
]

# Class bases that make a class a host-tier grain (turn discipline
# applies). VectorGrain is deliberately absent: its methods are kernel
# specs executed by the tick engine, not turns. (Shared with
# rules/common.py, which re-exports these helpers — rule modules import
# common, common imports this module, never the reverse.)
GRAIN_BASES = {
    "Grain", "StatefulGrain", "JournaledGrain", "TransactionalGrain",
    "GrainService",
}


def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains, "" for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def func_params(node: "ast.FunctionDef | ast.AsyncFunctionDef |"
                " ast.Lambda") -> set[str]:
    a = node.args
    names = {p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    return names

RELEASERS = {
    "recycle_message", "_recycle_callback", "recycle_callback",
    "_release_marker", "release_marker",
}

# batch releasers: release every ELEMENT of their container argument
# (the container itself stays live)
ITEM_RELEASERS = {"recycle_messages"}

# loop-confined observability classes and their mutating surface
REGISTRY_CLASSES = {"StatsRegistry", "Histogram", "QueueWaitTrend",
                    "SpanCollector", "CallSiteStats", "CostLedger"}
# distinctive enough to flag on ANY receiver (these names are only used
# as registry writes in this tree); see also _TYPED_WRITES
UNTYPED_WRITES = {"observe", "increment", "set_gauge", "exemplar", "note",
                  "charge_turn", "charge_tick", "charge_wire",
                  "charge_stream"}
# generic names: flagged only when the receiver's class is inferred
TYPED_WRITES = {"record", "histogram", "histogram_with", "force_retain",
                "mark_remote", "presampled", "pull", "merge"}

# loop-callback registration APIs: (method name, callable arg index)
_LOOP_CB_APIS = {"call_soon_threadsafe": 0, "call_soon": 0, "call_at": 1,
                 "call_later": 1, "add_reader": 1, "add_writer": 1,
                 "run_until_complete": 0}

# donated device state on fence-owning receivers (the PR-9 protocol)
PROTECTED_ATTRS = {"state", "hits", "cost"}

# Grain base-class methods that are NOT remote interface (mirrors
# runtime.grain._GRAIN_BASE_METHODS without importing the runtime)
_GRAIN_BASE_EXCLUDE = {
    "on_activate", "on_deactivate", "read_state", "write_state",
    "clear_state", "get_grain", "register_timer", "register_reminder",
    "unregister_reminder", "get_reminder", "get_stream_provider",
    "deactivate_on_idle", "delay_deactivation",
}


def _chain(node: ast.AST) -> tuple[str, ...]:
    """('self', 'tables') for self.tables; () when not a pure chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return ()


# ---------------------------------------------------------------------------
# Type specs: lazily-resolved descriptions of "what class is this value"
# ---------------------------------------------------------------------------
# spec forms:
#   ("cls", name)              — concrete class name
#   ("dict", valspec)          — dict with valspec values
#   ("expr", base, steps)      — walk: base ("var", name) | ("self",);
#                                steps: ("attr", a) | ("sub",) | ("call", m)
#   None                       — unknown

def _ann_spec(node: ast.AST):
    """Annotation AST → spec. Unwraps Optional[...] / ``X | None`` /
    quoted forward references; dict[...] keeps its value type so
    subscripts resolve element classes."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            node = ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return None
        return _ann_spec(node)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        left = _ann_spec(node.left)
        return left if left is not None else _ann_spec(node.right)
    if isinstance(node, ast.Subscript):
        head = dotted_name(node.value).rsplit(".", 1)[-1]
        if head in ("Optional",):
            return _ann_spec(node.slice)
        if head in ("dict", "Dict", "defaultdict"):
            sl = node.slice
            if isinstance(sl, ast.Tuple) and len(sl.elts) == 2:
                val = _ann_spec(sl.elts[1])
                if val is not None:
                    return ("dict", val)
            return None
        if head in ("list", "List", "tuple", "Tuple", "set", "Set"):
            return None
        return _ann_spec(node.value)
    name = dotted_name(node)
    if name:
        last = name.rsplit(".", 1)[-1]
        if last in ("None", "Any", "object", "int", "float", "str",
                    "bool", "bytes", "type", "Callable"):
            return None
        return ("cls", last)
    return None


def _expr_spec(node: ast.AST):
    """Value expression → spec (constructor call, attribute chain,
    subscript of a chain, or a method-call return)."""
    if isinstance(node, ast.Call):
        fn = node.func
        ch = _chain(fn)
        if len(ch) == 1 and ch[0][:1].isupper():
            return ("cls", ch[0])          # ClassName(...)
        if len(ch) > 1 and ch[-1][:1].isupper():
            return ("cls", ch[-1])         # mod.ClassName(...)
        if len(ch) >= 2:                   # obj.method(...): return type
            base = _expr_spec(fn.value)
            if base is not None:
                return _step(base, ("call", ch[-1]))
        return None
    if isinstance(node, ast.Await):
        return _expr_spec(node.value)
    if isinstance(node, ast.Subscript):
        base = _expr_spec(node.value)
        return _step(base, ("sub",)) if base is not None else None
    ch = _chain(node)
    if not ch:
        return None
    if ch[0] == "self":
        spec = ("expr", ("self",), ())
    else:
        spec = ("expr", ("var", ch[0]), ())
    for a in ch[1:]:
        spec = _step(spec, ("attr", a))
    return spec


def _step(spec, step):
    if spec is None:
        return None
    if spec[0] == "expr":
        return ("expr", spec[1], spec[2] + (step,))
    return ("expr", ("spec", spec), (step,))


# ---------------------------------------------------------------------------
# Summary dataclasses
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class CallEdge:
    chain: tuple[str, ...]          # callee as written: ("self","m")...
    lineno: int
    col: int
    args: tuple                     # positional arg Name ids (or None)
    kwargs: tuple                   # (name, is_none_literal|arg-name|True)
    nargs: int
    fenced: bool                    # lexically under a held tick fence
    none_args: frozenset            # positional indices passed literal None


@dataclass(frozen=True)
class SchedEdge:
    """A callable handed to a thread/executor/loop-scheduling API."""
    target: tuple[str, ...]         # chain of the callable passed
    kind: str                       # "thread" | "executor" | "loop" | "timer"
    loop: tuple | None              # receiver chain for kind == "loop"
    lineno: int
    api: str = ""                   # the registration API name


@dataclass(frozen=True)
class RegistryWrite:
    method: str
    recv: tuple[str, ...]
    lineno: int
    col: int
    guard: str | None               # param name guarding (stamp-and-replay)
    recv_is_param: str | None       # receiver IS this bare parameter


@dataclass(frozen=True)
class ProtectedAccess:
    attr: str
    recv: tuple[str, ...]
    lineno: int
    col: int
    fenced: bool


@dataclass
class FunctionSummary:
    qualname: str
    lineno: int
    params: tuple[str, ...] = ()
    is_async: bool = False
    releases: frozenset = frozenset()       # definite param releases
    releases_items: frozenset = frozenset()  # params whose ELEMENTS die
    escapes: frozenset = frozenset()
    returns_param: int | None = None
    calls: tuple[CallEdge, ...] = ()
    sched: tuple[SchedEdge, ...] = ()
    writes: tuple[RegistryWrite, ...] = ()
    protected: tuple[ProtectedAccess, ...] = ()
    var_specs: dict = field(default_factory=dict)   # name → spec
    has_releasers: bool = False             # direct releaser call present


@dataclass(frozen=True)
class GrainMethod:
    name: str
    min_pos: int                    # required positional (self excluded)
    max_pos: int | None             # None = *args
    kwonly: frozenset
    has_kwargs: bool
    one_way: bool


@dataclass
class GrainTable:
    name: str
    kind: str                       # "host" | "vector"
    bases: tuple[str, ...] = ()
    methods: dict = field(default_factory=dict)     # name → GrainMethod


@dataclass
class ClassInfo:
    name: str
    bases: tuple[str, ...] = ()
    is_thread: bool = False
    fence_owner: bool = False
    attr_specs: dict = field(default_factory=dict)  # attr → spec
    loop_attrs: dict = field(default_factory=dict)  # attr → "worker"|"main"
    method_returns: dict = field(default_factory=dict)  # meth → spec
    # shm-segment owner: assigns self.shm or lists "shm"/"buf" in
    # __slots__ — the OTPU010 ring-discipline scope marker
    shm_owner: bool = False
    # mutable container attrs: attr → "list"|"dict"|"set"|"deque"
    container_attrs: dict = field(default_factory=dict)


@dataclass
class ModuleSummary:
    rel_path: str
    module_key: str
    functions: dict = field(default_factory=dict)   # qualname → summary
    classes: dict = field(default_factory=dict)     # name → ClassInfo
    grains: dict = field(default_factory=dict)      # name → [GrainTable]
    imports: dict = field(default_factory=dict)     # name → (modkey, orig)
    globals_specs: dict = field(default_factory=dict)
    # ClassName.attr = ... monkey-patches: the attached name joins the
    # class's interface table as an open (unknown-arity) method
    grain_patches: list = field(default_factory=list)
    # qualname → function AST node. Retained for the link-time release
    # overlay (Program re-walks callers of cross-module releasers). A
    # pure function of the source text like everything else here, so
    # the content-hash cache stays sound; in-memory only.
    fn_nodes: dict = field(default_factory=dict, repr=False)


# ---------------------------------------------------------------------------
# Release dataflow walker (shared by phase 1 and the OTPU001 check)
# ---------------------------------------------------------------------------

_TERMINATED = None


class _Cell:
    __slots__ = ("gid", "released", "param")

    def __init__(self, gid, released=None, param=None):
        self.gid, self.released, self.param = gid, released, param


class ReleaseWalker:
    """Branch-aware, alias-aware, loop-carried released-state dataflow
    over ONE function body.

    State per path: ``bind`` maps name → (gid, released_line, param_idx);
    aliases share a ``gid`` so releasing any alias poisons the group.
    Branch merges keep DEFINITE facts only (released on all paths);
    loops run the body twice with the back-edge state merged in, so a
    release in iteration N is seen by a use in iteration N+1.

    Beyond bare names, 2-chain attributes (``self._pending``) are
    tracked as pseudo-variables that alias whatever was stored into
    them, and container membership (``batch.append(m)`` / ``d[k] = m``)
    is recorded per path so an ITEM-release of the container
    (``recycle_messages(batch)`` or a callee with a ``releases_items``
    summary) poisons the stashed members.

    ``release_of_call(call)`` maps a Call node to the names it releases
    ([] for unknown calls) — the interprocedural hook; ``alias_of_call``
    maps a Call to the argument Name its result aliases (or None);
    ``items_release_of_call`` maps a Call to the container names whose
    ELEMENTS it releases. Callbacks ``on_use(node, name, release_line)``
    and ``on_double(node, name)`` fire findings; both optional (summary
    mode records exit states instead).
    """

    _META = ("//rel//", "//mem//")

    def __init__(self, params: Iterable[str], release_of_call,
                 alias_of_call=None, on_use=None, on_double=None,
                 items_release_of_call=None):
        self._gids = itertools.count()
        self.release_of_call = release_of_call
        self.alias_of_call = alias_of_call or (lambda c: None)
        self.items_release_of_call = items_release_of_call or \
            (lambda c: [])
        self.on_use = on_use
        self.on_double = on_double
        self.reported: set = set()
        self.exit_releases: list[frozenset] = []
        self.return_params: list = []
        self.escaped: set[int] = set()
        self.items_released: set[int] = set()
        self.entry = {}
        for i, p in enumerate(params):
            self.entry[p] = (next(self._gids), None, i)

    @staticmethod
    def _attr_pseudo(node) -> str | None:
        """'a.b' pseudo-variable name for a 2-chain attribute."""
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name):
            return f"{node.value.id}.{node.attr}"
        return None

    @staticmethod
    def _cell_name(node) -> str | None:
        if isinstance(node, ast.Name):
            return node.id
        return ReleaseWalker._attr_pseudo(node)

    # -- state helpers --------------------------------------------------
    def _merge(self, states):
        live = [s for s in states if s is not _TERMINATED]
        if not live:
            return _TERMINATED
        if len(live) == 1:
            return live[0]
        merged = live[0]
        for other in live[1:]:
            out = {}
            memo: dict = {}
            rel0, rel1 = merged.get("//rel//"), other.get("//rel//")
            mem0 = merged.get("//mem//", frozenset())
            mem1 = other.get("//mem//", frozenset())
            for name, c in merged.items():
                if name in self._META:
                    continue
                o = other.get(name)
                if o is None:
                    continue
                if c[0] == o[0]:
                    # same alias group, but the branches may disagree on
                    # the release (a release REPLACES the cell per
                    # branch): definite semantics — released only when
                    # released on BOTH paths
                    if c[1] == o[1]:
                        out[name] = c
                    elif c[1] is not None and o[1] is not None:
                        out[name] = (c[0], min(c[1], o[1]), c[2])
                    else:
                        out[name] = (c[0], None, c[2])
                    continue
                key = (c[0], o[0])
                if key not in memo:
                    rel = c[1] if (c[1] is not None and o[1] is not None) \
                        else None
                    if rel is not None:
                        rel = min(c[1], o[1])
                    par = c[2] if c[2] == o[2] else None
                    memo[key] = (next(self._gids), rel, par)
                out[name] = memo[key]
            out["//rel//"] = (rel0 or frozenset()) & (rel1 or frozenset())
            # definite membership only: facts on both paths, and only
            # for alias groups that survived the merge un-remapped
            gids = {c[0] for n, c in out.items() if n not in self._META}
            out["//mem//"] = frozenset(
                t for t in (mem0 & mem1)
                if t[0] in gids and t[1] in gids)
            merged = out
        return merged

    @staticmethod
    def _rel_set(state) -> frozenset:
        return state.get("//rel//", frozenset())

    def run(self, body: list[ast.stmt]) -> None:
        state = dict(self.entry)
        state["//rel//"] = frozenset()
        state["//mem//"] = frozenset()
        end = self.exec_block(body, state)
        if end is not _TERMINATED:
            self.exit_releases.append(self._rel_set(end))
            self.return_params.append(None)

    def exec_block(self, stmts, state):
        for stmt in stmts:
            if state is _TERMINATED:
                return _TERMINATED
            state = self.exec_stmt(stmt, state)
        return state

    # -- per-statement events -------------------------------------------
    def _walk_shallow(self, root):
        stack = [root]
        while stack:
            node = stack.pop()
            if node is not root and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda, ast.ClassDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _release_events(self, stmt):
        out = []
        for node in self._walk_shallow(stmt):
            if isinstance(node, ast.Call):
                names = self.release_of_call(node)
                if names:
                    out.append((node, names))
        return out

    def _item_release_events(self, stmt):
        out = []
        for node in self._walk_shallow(stmt):
            if isinstance(node, ast.Call):
                names = self.items_release_of_call(node)
                if names:
                    out.append((node, names))
        return out

    def _emit_use(self, node, name, line):
        key = ("use", name, getattr(node, "lineno", 0))
        if self.on_use is not None and key not in self.reported:
            self.reported.add(key)
            self.on_use(node, name, line)

    def _apply_simple(self, stmt, state):
        releases = self._release_events(stmt)
        item_releases = self._item_release_events(stmt)
        # the arg Names a call releases are the release EVENT, not a
        # use — skip them in the use scan so a second release reports
        # as double-release, not use-after-release
        skip = set()
        for call, names in (*releases, *item_releases):
            for arg in (*call.args,
                        *(kw.value for kw in call.keywords)):
                if self._cell_name(arg) in names:
                    skip.add(id(arg))
        # uses first: the statement's loads see the PRE-statement state
        for node in self._walk_shallow(stmt):
            if id(node) in skip or not isinstance(
                    getattr(node, "ctx", None), ast.Load):
                continue
            if isinstance(node, ast.Name):
                c = state.get(node.id)
                if c is not None and c[1] is not None:
                    self._emit_use(node, node.id, c[1])
            elif isinstance(node, ast.Attribute):
                ps = self._attr_pseudo(node)
                c = state.get(ps) if ps is not None else None
                if c is not None and c[1] is not None:
                    self._emit_use(node, ps, c[1])
        # escapes: a param stored into a container/field
        self._scan_escapes(stmt, state)
        # container membership BEFORE releases: a same-statement stash
        # never outruns the release sweep
        self._scan_members(stmt, state)
        # releases
        for call, names in releases:
            for name in names:
                c = state.get(name)
                if c is None:
                    gid = next(self._gids)
                    state[name] = (gid, call.lineno, None)
                    continue
                if c[1] is not None:
                    key = ("double", name, call.lineno)
                    if self.on_double is not None and \
                            key not in self.reported:
                        self.reported.add(key)
                        self.on_double(call, name)
                    continue
                self._release_gid(state, c[0], call.lineno)
        # item releases: the container stays live, its members die
        for call, names in item_releases:
            for name in names:
                c = state.get(name)
                if c is None:
                    continue
                if c[2] is not None:
                    self.items_released.add(c[2])
                mem = state.get("//mem//", frozenset())
                for cont_gid, member_gid in mem:
                    if cont_gid == c[0]:
                        self._release_gid(state, member_gid, call.lineno,
                                          definite_only=True)
        # alias-aware rebinds (last: assignment targets bind AFTER rhs)
        self._apply_binds(stmt, state)
        return state

    def _release_gid(self, state, gid, lineno, definite_only=False):
        for n2, c2 in list(state.items()):
            if n2 in self._META or c2[0] != gid:
                continue
            if definite_only and c2[1] is not None:
                continue  # already released: no double-report for items
            state[n2] = (gid, lineno, c2[2])
            if c2[2] is not None:
                state["//rel//"] = self._rel_set(state) | {c2[2]}

    def _scan_escapes(self, stmt, state):
        for node in self._walk_shallow(stmt):
            names = []
            if isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Attribute) and fn.attr in (
                        "append", "add", "put", "put_nowait", "setdefault"):
                    names = [a for a in node.args
                             if isinstance(a, ast.Name)]
            elif isinstance(node, ast.Assign):
                if any(isinstance(t, (ast.Attribute, ast.Subscript))
                       for t in node.targets) and \
                        isinstance(node.value, ast.Name):
                    names = [node.value]
            for nm in names:
                c = state.get(nm.id)
                if c is not None and c[2] is not None:
                    self.escaped.add(c[2])

    def _scan_members(self, stmt, state):
        """Record container membership: ``c.append(m)`` / ``c[k] = m``
        links m's alias group to c's so an item-release of c poisons
        m."""
        for node in self._walk_shallow(stmt):
            cont = None
            members: list = []
            if isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Attribute) and node.args:
                    if fn.attr in ("append", "add", "put", "put_nowait"):
                        cont = self._cell_name(fn.value)
                        members = [node.args[0]]
                    elif fn.attr in ("setdefault", "insert") and \
                            len(node.args) > 1:
                        cont = self._cell_name(fn.value)
                        members = [node.args[1]]
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        cont = self._cell_name(t.value)
                        members = [node.value]
            if cont is None or not members:
                continue
            c = state.get(cont)
            if c is None:
                c = (next(self._gids), None, None)
                state[cont] = c
            add = set()
            for mnode in members:
                mname = self._cell_name(mnode)
                mc = state.get(mname) if mname is not None else None
                if mc is not None:
                    add.add((c[0], mc[0]))
            if add:
                state["//mem//"] = state.get("//mem//",
                                             frozenset()) | add

    def _bind_source(self, value, state):
        """The cell an assignment RHS aliases, or None."""
        if isinstance(value, ast.Name):
            return state.get(value.id)
        if isinstance(value, ast.Attribute):
            ps = self._attr_pseudo(value)
            return state.get(ps) if ps is not None else None
        if isinstance(value, ast.Call):
            al = self.alias_of_call(value)
            if al is not None:
                return state.get(al)
        return None

    def _invalidate_pseudo(self, state, base: str):
        """Rebinding ``x`` invalidates every tracked ``x.attr`` cell."""
        prefix = base + "."
        for k in [k for k in state
                  if k not in self._META and k.startswith(prefix)]:
            state[k] = (next(self._gids), None, None)

    def _apply_binds(self, stmt, state):
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            t = stmt.targets[0]
            if isinstance(t, ast.Name):
                src = self._bind_source(stmt.value, state)
                self._invalidate_pseudo(state, t.id)
                if src is not None:
                    state[t.id] = src       # alias: share the gid
                    return
                state[t.id] = (next(self._gids), None, None)
                return
            ps = self._attr_pseudo(t)
            if ps is not None:
                src = self._bind_source(stmt.value, state)
                state[ps] = src if src is not None else \
                    (next(self._gids), None, None)
                return
        for node in self._walk_shallow(stmt):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)):
                if node.id in state:
                    state[node.id] = (next(self._gids), None, None)
                self._invalidate_pseudo(state, node.id)

    # -- control flow ----------------------------------------------------
    def exec_stmt(self, stmt, state):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            state.pop(stmt.name, None)
            return state
        if isinstance(stmt, ast.Return):
            self._apply_simple(stmt, state)
            self.exit_releases.append(self._rel_set(state))
            rp = None
            if isinstance(stmt.value, ast.Name):
                c = state.get(stmt.value.id)
                if c is not None:
                    rp = c[2]
            self.return_params.append(rp)
            return _TERMINATED
        if isinstance(stmt, ast.Raise):
            self._apply_simple(stmt, state)
            return _TERMINATED
        if isinstance(stmt, (ast.Break, ast.Continue)):
            return _TERMINATED
        if isinstance(stmt, ast.If):
            self._apply_simple(ast.Expr(stmt.test), state)
            s_body = self.exec_block(stmt.body, dict(state))
            s_else = self.exec_block(stmt.orelse, dict(state))
            return self._merge([s_body, s_else])
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            if isinstance(stmt, ast.While):
                self._apply_simple(ast.Expr(stmt.test), state)
            else:
                self._apply_simple(ast.Expr(stmt.iter), state)
            entry = dict(state)

            def rebind_targets(st):
                if not isinstance(stmt, ast.While):
                    for node in ast.walk(stmt.target):
                        if isinstance(node, ast.Name):
                            st[node.id] = (next(self._gids), None, None)
                return st

            rebind_targets(entry)
            # pass 1: straight-line release→use inside one iteration
            exit1 = self.exec_block(stmt.body, dict(entry))
            # pass 2 runs the body again FROM the iteration-exit state:
            # a definite release at the end of iteration N reaches a use
            # in iteration N+1 (loop-carried). Break/continue paths
            # terminate and so never feed the back edge — a
            # release-then-break body stays clean.
            if exit1 is not _TERMINATED:
                self.exec_block(stmt.body, rebind_targets(dict(exit1)))
            after = self._merge([dict(state), exit1])
            if after is _TERMINATED:
                after = dict(state)
            self.exec_block(stmt.orelse, dict(after))
            return after
        if isinstance(stmt, (ast.Try, getattr(ast, "TryStar", ast.Try))):
            s_body = self.exec_block(stmt.body, dict(state))
            if s_body is not _TERMINATED and stmt.orelse:
                s_body = self.exec_block(stmt.orelse, s_body)
            ends = [s_body]
            for handler in stmt.handlers:
                ends.append(self.exec_block(handler.body, dict(state)))
            merged = self._merge(ends)
            fin_in = merged if merged is not _TERMINATED else dict(state)
            fin_out = self.exec_block(stmt.finalbody, dict(fin_in))
            if merged is _TERMINATED or fin_out is _TERMINATED:
                return _TERMINATED
            return fin_out
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._apply_simple(ast.Expr(item.context_expr), state)
                if item.optional_vars is not None:
                    for node in ast.walk(item.optional_vars):
                        if isinstance(node, ast.Name):
                            state[node.id] = (next(self._gids), None, None)
            return self.exec_block(stmt.body, state)
        match_cls = getattr(ast, "Match", None)
        if match_cls is not None and isinstance(stmt, match_cls):
            self._apply_simple(ast.Expr(stmt.subject), state)
            ends = [self.exec_block(case.body, dict(state))
                    for case in stmt.cases]
            ends.append(dict(state))
            return self._merge(ends)
        return self._apply_simple(stmt, state)

    # -- summary products ------------------------------------------------
    def definite_releases(self) -> frozenset:
        if not self.exit_releases:
            return frozenset()
        out = self.exit_releases[0]
        for s in self.exit_releases[1:]:
            out = out & s
        return out

    def returned_param(self):
        vals = {v for v in self.return_params}
        if len(vals) == 1:
            v = vals.pop()
            return v
        return None


# ---------------------------------------------------------------------------
# Phase 1: one module → ModuleSummary
# ---------------------------------------------------------------------------

def _module_key(rel_path: str) -> str:
    key = rel_path[:-3] if rel_path.endswith(".py") else rel_path
    key = key.replace("/", ".")
    return key[:-9] if key.endswith(".__init__") else key


def _fence_exprs(item_expr: ast.AST) -> bool:
    """Is this with-item a tick-fence acquisition? ``x.fence`` /
    ``x._fence`` attribute, or an ``x.tick_fence()`` call."""
    if isinstance(item_expr, ast.Call):
        ch = _chain(item_expr.func)
        return bool(ch) and ch[-1] in ("tick_fence", "fence", "_fence")
    ch = _chain(item_expr)
    return bool(ch) and ch[-1] in ("fence", "_fence")


class _FuncCollector:
    """Single source-ordered walk of one function body collecting call
    edges, scheduling edges, registry writes, protected accesses and
    local type specs — with the lexical fence/guard context threaded
    through the recursion."""

    def __init__(self, fn, qualname: str):
        self.fn = fn
        self.summary = FunctionSummary(
            qualname=qualname, lineno=fn.lineno,
            params=tuple(self._pos_params(fn)),
            is_async=isinstance(fn, ast.AsyncFunctionDef))
        self.calls: list[CallEdge] = []
        self.sched: list[SchedEdge] = []
        self.writes: list[RegistryWrite] = []
        self.protected: list[ProtectedAccess] = []
        self.var_specs: dict = {}
        self.param_set = func_params(fn)
        a = fn.args
        for p in (*a.posonlyargs, *a.args, *a.kwonlyargs):
            spec = _ann_spec(p.annotation)
            if spec is not None:
                self.var_specs[p.arg] = spec
        self._has_releasers = False

    @staticmethod
    def _pos_params(fn) -> list[str]:
        a = fn.args
        return [p.arg for p in (*a.posonlyargs, *a.args)]

    def collect(self):
        self._block(self.fn.body, fenced=False, guard=None)
        s = self.summary
        s.calls = tuple(self.calls)
        s.sched = tuple(self.sched)
        s.writes = tuple(self.writes)
        s.protected = tuple(self.protected)
        s.var_specs = self.var_specs
        s.has_releasers = self._has_releasers
        s.returns_param = self._returns_param()
        return s

    def _returns_param(self):
        """Cheap identity-function detection: every return in the body
        returns the SAME bare parameter and the body never rebinds it —
        callers then treat the result as an alias of the argument. (The
        release walker recomputes this precisely for releasing
        functions; this scan covers plain pass-through helpers.)"""
        returned: set = set()
        params = list(self.summary.params)
        stack: list = list(self.fn.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue  # nested scope: its returns are not ours
            if isinstance(node, ast.Return):
                if not isinstance(node.value, ast.Name):
                    return None
                returned.add(node.value.id)
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)) and \
                    node.id in params:
                return None
            stack.extend(ast.iter_child_nodes(node))
        if len(returned) == 1:
            name = returned.pop()
            if name in params:
                return params.index(name)
        return None

    # -- recursion ------------------------------------------------------
    def _block(self, stmts, fenced: bool, guard):
        for stmt in stmts:
            self._stmt(stmt, fenced, guard)

    def _stmt(self, stmt, fenced, guard):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs are summarized as their own functions
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            now_fenced = fenced
            for item in stmt.items:
                self._expr(item.context_expr, fenced, guard)
                if _fence_exprs(item.context_expr):
                    now_fenced = True
            self._block(stmt.body, now_fenced, guard)
            return
        if isinstance(stmt, ast.If):
            self._expr(stmt.test, fenced, guard)
            g = self._none_guard(stmt.test)
            if g is not None:
                name, none_branch = g
                self._block(stmt.body, fenced,
                            name if none_branch else guard)
                self._block(stmt.orelse, fenced,
                            guard if none_branch else name)
                return
            self._block(stmt.body, fenced, guard)
            self._block(stmt.orelse, fenced, guard)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, fenced, guard)
            self._type_for_target(stmt.target, stmt.iter)
            self._block(stmt.body, fenced, guard)
            self._block(stmt.orelse, fenced, guard)
            return
        if isinstance(stmt, ast.While):
            self._expr(stmt.test, fenced, guard)
            self._block(stmt.body, fenced, guard)
            self._block(stmt.orelse, fenced, guard)
            return
        if isinstance(stmt, (ast.Try, getattr(ast, "TryStar", ast.Try))):
            self._block(stmt.body, fenced, guard)
            for h in stmt.handlers:
                self._block(h.body, fenced, guard)
            self._block(stmt.orelse, fenced, guard)
            self._block(stmt.finalbody, fenced, guard)
            return
        if isinstance(stmt, ast.Assign):
            self._expr(stmt.value, fenced, guard)
            for t in stmt.targets:
                self._maybe_protected(t, fenced, store=True)
            if len(stmt.targets) == 1 and \
                    isinstance(stmt.targets[0], ast.Name):
                spec = _expr_spec(stmt.value)
                if spec is not None:
                    self.var_specs[stmt.targets[0].id] = spec
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._expr(stmt.value, fenced, guard)
            self._maybe_protected(stmt.target, fenced, store=True)
            if isinstance(stmt.target, ast.Name):
                spec = _ann_spec(stmt.annotation)
                if spec is not None:
                    self.var_specs[stmt.target.id] = spec
            return
        if isinstance(stmt, ast.AugAssign):
            self._expr(stmt.value, fenced, guard)
            self._maybe_protected(stmt.target, fenced, store=True)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._expr(child, fenced, guard)
            elif isinstance(child, ast.stmt):
                self._stmt(child, fenced, guard)

    @staticmethod
    def _none_guard(test):
        """``x is None`` / ``x is not None`` / bare ``x`` / ``not x`` for
        a simple name → (name, none_branch_is_body). The guard threads
        into the branch where x may be None — the stamp-and-replay
        detector keys on it."""
        if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
                isinstance(test.left, ast.Name) and \
                isinstance(test.comparators[0], ast.Constant) and \
                test.comparators[0].value is None:
            if isinstance(test.ops[0], ast.Is):
                return test.left.id, True
            if isinstance(test.ops[0], ast.IsNot):
                return test.left.id, False
        if isinstance(test, ast.Name):
            return test.id, False
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
                and isinstance(test.operand, ast.Name):
            return test.operand.id, True
        return None

    def _type_for_target(self, target, iter_expr):
        """``for cls, tbl in X.tables.items()`` → tbl: dict-value type."""
        if not (isinstance(iter_expr, ast.Call) and
                isinstance(iter_expr.func, ast.Attribute)):
            return
        meth = iter_expr.func.attr
        if meth not in ("items", "values"):
            return
        base = _expr_spec(iter_expr.func.value)
        if base is None:
            return
        val = ("expr", ("spec", base), (("dictval",),)) \
            if base[0] != "dict" else base[1]
        if meth == "values" and isinstance(target, ast.Name):
            self.var_specs[target.id] = val
        elif meth == "items" and isinstance(target, ast.Tuple) and \
                len(target.elts) == 2 and \
                isinstance(target.elts[1], ast.Name):
            self.var_specs[target.elts[1].id] = val

    # -- expressions ----------------------------------------------------
    def _expr(self, node, fenced, guard):
        if isinstance(node, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            return
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            for gen in node.generators:
                self._expr(gen.iter, fenced, guard)
                self._type_for_target(gen.target, gen.iter)
                for cond in gen.ifs:
                    self._expr(cond, fenced, guard)
            if isinstance(node, ast.DictComp):
                self._expr(node.key, fenced, guard)
                self._expr(node.value, fenced, guard)
            else:
                self._expr(node.elt, fenced, guard)
            return
        if isinstance(node, ast.Call):
            self._call(node, fenced, guard)
            if isinstance(node.func, ast.Attribute):
                # x.state.values(): the protected attr hides inside the
                # callee chain, which _call does not treat as a load
                self._maybe_protected(node.func.value, fenced,
                                      store=False)
            for a in node.args:
                if not isinstance(a, ast.Starred):
                    self._expr(a, fenced, guard)
                else:
                    self._expr(a.value, fenced, guard)
            for kw in node.keywords:
                self._expr(kw.value, fenced, guard)
            return
        self._maybe_protected(node, fenced, store=False)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child, fenced, guard)

    def _maybe_protected(self, node, fenced, store):
        tgt = node
        while isinstance(tgt, ast.Subscript):
            tgt = tgt.value
        if isinstance(tgt, ast.Attribute) and tgt.attr in PROTECTED_ATTRS:
            ch = _chain(tgt)
            if ch:
                self.protected.append(ProtectedAccess(
                    tgt.attr, ch[:-1], tgt.lineno, tgt.col_offset + 1,
                    fenced))

    def _call(self, node: ast.Call, fenced, guard):
        ch = _chain(node.func)
        if not ch:
            return
        name = ch[-1]
        if name in RELEASERS:
            self._has_releasers = True
        # -- scheduling / spawning edges --------------------------------
        if name == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    tch = _chain(kw.value)
                    if tch:
                        self.sched.append(SchedEdge(
                            tch, "thread", None, node.lineno,
                            api="Thread"))
        elif name == "run_in_executor" and len(node.args) >= 2:
            tch = _chain(node.args[1])
            if tch:
                self.sched.append(SchedEdge(
                    tch, "executor", None, node.lineno,
                    api="run_in_executor"))
            elif isinstance(node.args[1], ast.Lambda):
                self.sched.append(SchedEdge(
                    (f"<lambda@{node.args[1].lineno}>",), "executor",
                    None, node.lineno, api="run_in_executor"))
        elif name in _LOOP_CB_APIS and len(ch) >= 2:
            idx = _LOOP_CB_APIS[name]
            if len(node.args) > idx:
                tch = _chain(node.args[idx])
                if tch:
                    self.sched.append(SchedEdge(
                        tch, "loop", ch[:-1], node.lineno, api=name))
        elif name == "create_task" and len(ch) >= 2 and node.args:
            inner = node.args[0]
            if isinstance(inner, ast.Call):
                tch = _chain(inner.func)
                if tch:
                    self.sched.append(SchedEdge(
                        tch, "loop", ch[:-1], node.lineno,
                        api="create_task"))
        elif name == "register_timer" and node.args:
            # grain/activation timers: the callback fires as a turn on
            # the silo main loop — a declared entry point at link time
            tch = _chain(node.args[0])
            if tch:
                self.sched.append(SchedEdge(
                    tch, "timer", None, node.lineno,
                    api="register_timer"))
        # -- registry writes --------------------------------------------
        if len(ch) >= 2 and (name in UNTYPED_WRITES or
                             name in TYPED_WRITES):
            recv = ch[:-1]
            recv_is_param = recv[0] if (
                len(recv) == 1 and recv[0] in self.param_set) else None
            self.writes.append(RegistryWrite(
                name, recv, node.lineno, node.col_offset + 1,
                guard, recv_is_param))
        # -- plain call edge --------------------------------------------
        args = tuple(a.id if isinstance(a, ast.Name) else None
                     for a in node.args)
        none_args = frozenset(
            i for i, a in enumerate(node.args)
            if isinstance(a, ast.Constant) and a.value is None)
        kwargs = tuple(
            (kw.arg, (kw.value.value is None
                      if isinstance(kw.value, ast.Constant) else
                      kw.value.id if isinstance(kw.value, ast.Name)
                      else False))
            for kw in node.keywords if kw.arg is not None)
        self.calls.append(CallEdge(
            ch, node.lineno, node.col_offset + 1, args, kwargs,
            len(node.args), fenced, none_args))


def _grain_method(fn) -> GrainMethod:
    a = fn.args
    pos = [p.arg for p in (*a.posonlyargs, *a.args)]
    if pos and pos[0] in ("self", "cls"):
        pos = pos[1:]
    n_def = len(a.defaults)
    min_pos = max(0, len(pos) - n_def)
    max_pos = None if a.vararg else len(pos)
    kwonly = frozenset(p.arg for p in a.kwonlyargs) | frozenset(pos)
    one_way = any(
        dotted_name(d if not isinstance(d, ast.Call) else d.func)
        .rsplit(".", 1)[-1] == "one_way" for d in fn.decorator_list)
    return GrainMethod(fn.name, min_pos, max_pos, kwonly,
                       a.kwarg is not None, one_way)


def _container_kind(val) -> str | None:
    """'list'|'dict'|'set'|'deque' for a container-constructor RHS."""
    if isinstance(val, ast.List):
        return "list"
    if isinstance(val, (ast.Dict, ast.DictComp)):
        return "dict"
    if isinstance(val, (ast.Set, ast.SetComp)):
        return "set"
    if isinstance(val, ast.ListComp):
        return "list"
    if isinstance(val, ast.Call):
        ch = _chain(val.func)
        if ch and ch[-1] in ("list", "dict", "set", "deque",
                             "defaultdict", "OrderedDict", "Counter"):
            return "deque" if ch[-1] == "deque" else (
                "dict" if ch[-1] in ("dict", "defaultdict",
                                     "OrderedDict", "Counter")
                else ch[-1])
    return None


def _class_info(node: ast.ClassDef) -> ClassInfo:
    bases = tuple(dotted_name(b).rsplit(".", 1)[-1] for b in node.bases
                  if dotted_name(b))
    info = ClassInfo(node.name, bases=bases,
                     is_thread="Thread" in bases)
    for stmt in node.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                stmt.targets[0].id == "__slots__":
            slots = {e.value for e in ast.walk(stmt.value)
                     if isinstance(e, ast.Constant) and
                     isinstance(e.value, str)}
            if "shm" in slots:
                info.shm_owner = True
        if isinstance(stmt, ast.AnnAssign) and \
                isinstance(stmt.target, ast.Name):
            spec = _ann_spec(stmt.annotation)
            if spec is not None:
                info.attr_specs[stmt.target.id] = spec
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            ret = _ann_spec(stmt.returns)
            if ret is not None:
                info.method_returns[stmt.name] = ret
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    t = sub.targets[0]
                    tch = _chain(t)
                    if len(tch) == 2 and tch[0] == "self":
                        attr = tch[1]
                        if attr in ("fence", "_fence"):
                            info.fence_owner = True
                        if attr == "shm":
                            info.shm_owner = True
                        val = sub.value
                        ckind = _container_kind(val)
                        if ckind is not None:
                            info.container_attrs.setdefault(attr, ckind)
                        vch = _chain(val if not isinstance(val, ast.Call)
                                     else val.func)
                        if isinstance(val, ast.Call):
                            if vch[-2:] == ("asyncio", "new_event_loop") \
                                    or vch == ("new_event_loop",):
                                info.loop_attrs[attr] = "worker"
                            elif vch and vch[-1] in (
                                    "get_running_loop", "get_event_loop"):
                                info.loop_attrs[attr] = "main"
                        if attr not in info.attr_specs:
                            spec = _expr_spec(val)
                            if spec is not None:
                                info.attr_specs[attr] = spec
                elif isinstance(sub, ast.AnnAssign):
                    tch = _chain(sub.target)
                    if len(tch) == 2 and tch[0] == "self":
                        spec = _ann_spec(sub.annotation)
                        if spec is not None:
                            info.attr_specs.setdefault(tch[1], spec)
    return info


def _grain_table(node: ast.ClassDef, kind: str) -> GrainTable:
    bases = tuple(dotted_name(b).rsplit(".", 1)[-1] for b in node.bases
                  if dotted_name(b))
    tbl = GrainTable(node.name, kind, bases=bases)
    for stmt in node.body:
        if kind == "host":
            if isinstance(stmt, ast.AsyncFunctionDef) and \
                    not stmt.name.startswith("_") and \
                    stmt.name not in _GRAIN_BASE_EXCLUDE:
                tbl.methods[stmt.name] = _grain_method(stmt)
        else:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for d in stmt.decorator_list:
                    dn = dotted_name(d if not isinstance(d, ast.Call)
                                     else d.func).rsplit(".", 1)[-1]
                    if dn == "actor_method":
                        tbl.methods[stmt.name] = _grain_method(stmt)
    return tbl


_VECTOR_BASES = {"VectorGrain"}


def summarize_module(source: str, rel_path: str,
                     tree: ast.Module | None = None) -> ModuleSummary:
    if tree is None:
        tree = ast.parse(source)
    ms = ModuleSummary(rel_path=rel_path.replace("\\", "/"),
                       module_key=_module_key(rel_path))
    pkg_parts = ms.module_key.split(".")

    for stmt in tree.body:
        if isinstance(stmt, ast.ImportFrom):
            if stmt.level:
                base = pkg_parts[:-stmt.level] if stmt.level <= \
                    len(pkg_parts) else []
                mod = ".".join(base + ([stmt.module] if stmt.module
                                       else []))
            else:
                mod = stmt.module or ""
            for alias in stmt.names:
                ms.imports[alias.asname or alias.name] = (mod, alias.name)
        elif isinstance(stmt, ast.Import):
            for alias in stmt.names:
                ms.imports[alias.asname or alias.name] = (alias.name, "")
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            spec = _expr_spec(stmt.value)
            if spec is not None:
                ms.globals_specs[stmt.targets[0].id] = spec

    fn_nodes: dict = {}

    def visit(node, prefix, cls_name):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                fn_nodes[qn] = child
                ms.functions[qn] = _FuncCollector(child, qn).collect()
                visit(child, qn + ".", cls_name)
                # lambdas handed to executors get synthetic empty
                # summaries so scheduling edges resolve to something
                for sub in ast.walk(child):
                    if isinstance(sub, ast.Lambda):
                        lqn = f"{qn}.<lambda@{sub.lineno}>"
                        body = ast.Expr(sub.body)
                        ast.copy_location(body, sub.body)
                        shim = ast.FunctionDef(
                            name=lqn, args=sub.args, body=[body],
                            decorator_list=[], returns=None,
                            type_comment=None)
                        ast.copy_location(shim, sub)
                        ms.functions[lqn] = _FuncCollector(
                            shim, lqn).collect()
            elif isinstance(child, ast.ClassDef):
                qn = f"{prefix}{child.name}"
                info = _class_info(child)
                ms.classes[child.name] = info
                base_last = {b for b in info.bases}
                if base_last & GRAIN_BASES:
                    ms.grains.setdefault(child.name, []).append(
                        _grain_table(child, "host"))
                elif base_last & _VECTOR_BASES:
                    ms.grains.setdefault(child.name, []).append(
                        _grain_table(child, "vector"))
                visit(child, qn + ".", child.name)

    visit(tree, "", None)
    # ClassName.method = fn monkey-patches widen the interface table:
    # the attached name becomes an open (unknown-arity) method, so the
    # typed checks never flag a dynamically-grafted entry point
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id[:1].isupper():
                    ms.grain_patches.append((t.value.id, t.attr))
    _close_releases(ms, fn_nodes)
    ms.fn_nodes = fn_nodes
    return ms


def resolve_local(ms: ModuleSummary, caller_qual: str,
                  chain: tuple) -> str | None:
    """Module-local callee resolution: bare names search the caller's
    enclosing scopes then the top level; ``self.m`` searches the
    enclosing class (no base-class walk here — that is phase 2)."""
    if len(chain) == 1:
        name = chain[0]
        parts = caller_qual.split(".")
        for i in range(len(parts), 0, -1):
            cand = ".".join(parts[:i]) + "." + name
            if cand in ms.functions:
                return cand
        return name if name in ms.functions else None
    if len(chain) == 2 and chain[0] in ("self", "cls"):
        parts = caller_qual.split(".")
        for i in range(len(parts) - 1, 0, -1):
            if parts[i - 1] in ms.classes:
                cand = ".".join(parts[:i]) + "." + chain[1]
                if cand in ms.functions:
                    return cand
        return None
    return None


def _arg_cell_name(node) -> str | None:
    """Name id or 2-chain attribute pseudo-name for a call argument."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name):
        return f"{node.value.id}.{node.attr}"
    return None


def _callee_summary(ms: ModuleSummary, caller_qual: str, ch: tuple,
                    extern=None):
    """Resolve a call chain to a FunctionSummary. ``extern(chain) ->
    FunctionSummary|None`` extends resolution across modules at
    link/check time and is consulted FIRST when present: the Program
    hook resolves locals too and applies its release overlay, which is
    how an edit to module A re-judges B's edges into A without
    re-summarizing B."""
    if extern is not None:
        summ = extern(ch)
        if summ is not None:
            return summ
    local = resolve_local(ms, caller_qual, ch)
    return ms.functions[local] if local is not None else None


def _param_args(summ, ch: tuple, call: ast.Call,
                indices) -> list:
    """Map callee param indices to caller-side cell names."""
    out = []
    offset = 1 if (summ.params and summ.params[0] in ("self", "cls")
                   and len(ch) >= 2) else 0
    for j in sorted(indices):
        pos = j - offset
        if 0 <= pos < len(call.args):
            nm = _arg_cell_name(call.args[pos])
            if nm is not None:
                out.append(nm)
                continue
        if j < len(summ.params):
            pname = summ.params[j]
            for kw in call.keywords:
                if kw.arg == pname:
                    nm = _arg_cell_name(kw.value)
                    if nm is not None:
                        out.append(nm)
    return out


def _call_releases(ms: ModuleSummary, caller_qual: str, call: ast.Call,
                   extern=None) -> list:
    """Names a Call releases: the direct releasers, plus calls to
    functions whose (current) summary definitely releases a
    parameter."""
    ch = _chain(call.func)
    if not ch:
        return []
    if ch[-1] in RELEASERS and call.args:
        nm = _arg_cell_name(call.args[0])
        return [nm] if nm is not None else []
    summ = _callee_summary(ms, caller_qual, ch, extern)
    if summ is None or not summ.releases:
        return []
    return _param_args(summ, ch, call, summ.releases)


def _call_releases_items(ms: ModuleSummary, caller_qual: str,
                         call: ast.Call, extern=None) -> list:
    """Container names whose ELEMENTS a Call releases (the container
    itself stays live): the batch releasers, plus calls to functions
    with a ``releases_items`` summary."""
    ch = _chain(call.func)
    if not ch:
        return []
    if ch[-1] in ITEM_RELEASERS and call.args:
        nm = _arg_cell_name(call.args[0])
        return [nm] if nm is not None else []
    summ = _callee_summary(ms, caller_qual, ch, extern)
    if summ is None or not summ.releases_items:
        return []
    return _param_args(summ, ch, call, summ.releases_items)


def _call_alias(ms: ModuleSummary, caller_qual: str, call: ast.Call,
                extern=None) -> str | None:
    """The argument Name a call's RESULT aliases (callee returns one of
    its parameters), or None."""
    ch = _chain(call.func)
    if not ch:
        return None
    summ = _callee_summary(ms, caller_qual, ch, extern)
    if summ is None or summ.returns_param is None:
        return None
    offset = 1 if (summ.params and summ.params[0] in ("self", "cls")
                   and len(ch) >= 2) else 0
    pos = summ.returns_param - offset
    if 0 <= pos < len(call.args):
        return _arg_cell_name(call.args[pos])
    return None


def _for_loop_item_releases(ms: ModuleSummary, qual: str, fn,
                            extern=None) -> frozenset:
    """Param indices whose ELEMENTS the function definitely releases via
    the ``for m in batch: recycle_message(m)`` idiom (direct body
    statements only — a conditional release is not definite for the
    stash the caller tracked)."""
    params = _FuncCollector._pos_params(fn)
    out: set[int] = set()
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))
        if not isinstance(node, ast.For) or \
                not isinstance(node.target, ast.Name):
            continue
        it = node.iter
        base = None
        if isinstance(it, ast.Name):
            base = it.id
        elif isinstance(it, ast.Call) and \
                isinstance(it.func, ast.Attribute) and \
                it.func.attr in ("values",) and \
                isinstance(it.func.value, ast.Name):
            base = it.func.value.id
        if base not in params:
            continue
        t = node.target.id
        for stmt in node.body:
            if isinstance(stmt, (ast.If, ast.For, ast.While, ast.Try,
                                 ast.With)):
                continue
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call) and t in _call_releases(
                        ms, qual, sub, extern):
                    out.add(params.index(base))
    return frozenset(out)


def _summarize_releases(ms: ModuleSummary, qual: str, fn,
                        extern=None) -> tuple:
    """(releases, returns_param, escapes, releases_items) for one
    function via the dataflow walker, consulting the module's current
    summaries (plus ``extern`` at link time) for callee release
    behavior."""
    params = _FuncCollector._pos_params(fn)
    walker = ReleaseWalker(
        params,
        release_of_call=lambda c: _call_releases(ms, qual, c, extern),
        alias_of_call=lambda c: _call_alias(ms, qual, c, extern),
        items_release_of_call=lambda c: _call_releases_items(
            ms, qual, c, extern))
    walker.run(fn.body)
    items = frozenset(walker.items_released) | \
        _for_loop_item_releases(ms, qual, fn, extern)
    return (walker.definite_releases(), walker.returned_param(),
            frozenset(walker.escaped), items)


def _close_releases(ms: ModuleSummary, fn_nodes: dict) -> None:
    """Module-local transitive release closure: seed with functions that
    call a releaser directly, then re-walk callers of releasing
    functions until the summaries stop changing (bounded — chains in
    practice are 2-3 deep). Cross-module closure happens at link time
    via the Program's release overlay."""
    releasing_names: set[str] = set()
    for qual, s in ms.functions.items():
        if not s.has_releasers and not any(
                e.chain[-1] in ITEM_RELEASERS for e in s.calls):
            continue
        rel, ret, esc, items = _summarize_releases(
            ms, qual, fn_nodes[qual])
        s.releases, s.returns_param, s.escapes = rel, ret, esc
        s.releases_items = items
        if rel or items:
            releasing_names.add(qual.rsplit(".", 1)[-1])
    if not releasing_names:
        return
    for _ in range(4):
        changed = False
        for qual, s in ms.functions.items():
            if qual not in fn_nodes:
                continue
            calls_releasing = any(
                e.chain[-1] in releasing_names or
                e.chain[-1] in RELEASERS or
                e.chain[-1] in ITEM_RELEASERS for e in s.calls)
            if not calls_releasing:
                continue
            rel, ret, esc, items = _summarize_releases(
                ms, qual, fn_nodes[qual])
            if rel != s.releases or ret != s.returns_param or \
                    items != s.releases_items:
                changed = True
                s.releases, s.returns_param, s.escapes = rel, ret, esc
                s.releases_items = items
                if rel or items:
                    releasing_names.add(qual.rsplit(".", 1)[-1])
        if not changed:
            break


# phase-1 cache: content hash → ModuleSummary (summaries are pure
# functions of the source text; phase 2 never mutates them)
_CACHE: dict = {}
_CACHE_CAP = 4096
# monotonic counters for --stats; callers snapshot-and-diff around a run
CACHE_STATS = {"hits": 0, "misses": 0}


def module_summary(source: str, rel_path: str,
                   tree: ast.Module | None = None) -> ModuleSummary:
    key = (hashlib.sha1(source.encode("utf-8", "replace")).hexdigest(),
           rel_path)
    hit = _CACHE.get(key)
    if hit is not None:
        CACHE_STATS["hits"] += 1
        return hit
    CACHE_STATS["misses"] += 1
    ms = summarize_module(source, rel_path, tree)
    if len(_CACHE) >= _CACHE_CAP:
        _CACHE.clear()
    _CACHE[key] = ms
    return ms


# ---------------------------------------------------------------------------
# Phase 2: link ModuleSummaries into a Program
# ---------------------------------------------------------------------------

class Program:
    """The linked view rules query: cross-module call resolution, the
    worker-context set (with reasons), the fence-held fixpoint, resolved
    receiver types, and merged grain interface tables. Built fresh per
    analysis run from cached per-module summaries — linking is cheap,
    summarizing is not."""

    def __init__(self, modules: list[ModuleSummary]):
        self.modules: dict[str, ModuleSummary] = {
            m.module_key: m for m in modules}
        self.by_rel: dict[str, ModuleSummary] = {
            m.rel_path: m for m in modules}
        # dotted-suffix index: an import records the module string as
        # WRITTEN ('from ring_helper import free'), but module keys are
        # derived from scan-root-relative paths, so a sibling import
        # carries no directory prefix. A unique dotted suffix resolves;
        # an ambiguous one stays unresolved (None tombstone).
        self._suffix_index: dict[str, str | None] = {}
        for key in self.modules:
            parts = key.split(".")
            for i in range(len(parts)):
                suf = ".".join(parts[i:])
                if suf in self._suffix_index and \
                        self._suffix_index[suf] != key:
                    self._suffix_index[suf] = None
                else:
                    self._suffix_index[suf] = key
        # class name → (module, ClassInfo); first definition wins, which
        # is fine for THIS tree (no duplicate class names across layers)
        self.class_index: dict[str, tuple] = {}
        for m in modules:
            for name, info in m.classes.items():
                self.class_index.setdefault(name, (m, info))
        self.grains: dict[str, GrainTable] = {}
        self._merge_grains(modules)
        # (module_key, qualname) → summary
        self.functions: dict[tuple, FunctionSummary] = {}
        for m in modules:
            for q, s in m.functions.items():
                self.functions[(m.module_key, q)] = s
        self._call_sites: dict[tuple, list] = {}
        self._index_call_sites()
        # declared entry-point contexts (ctl_* handlers, timer and
        # loop-scheduled callbacks, ring drains): key → label
        self.entry_contexts: dict[tuple, str] = {}
        self._collect_entry_contexts()
        self.worker: dict[tuple, str] = {}
        self.worker_seeds: set = set()
        self._worker_fixpoint()
        self._worker_kind: dict[tuple, str] = {}
        self._classify_worker_contexts()
        self.held: dict[tuple, bool] = {}
        self._fence_fixpoint()
        # link-time cross-module release closure (phase 2 NEVER mutates
        # the cached summaries — re-judged facts live here)
        self._rel_overlay: dict[tuple, tuple] = {}
        self._release_overlay()

    # -- grain tables ----------------------------------------------------
    def _merge_grains(self, modules):
        raw: dict[str, list] = {}
        for m in modules:
            for name, tables in m.grains.items():
                raw.setdefault(name, []).extend(tables)
        for name, tables in raw.items():
            if len(tables) == 1:
                merged = GrainTable(name, tables[0].kind,
                                    tables[0].bases,
                                    dict(tables[0].methods))
            else:
                # same-name grain classes in different modules: union the
                # methods and widen arity — never a false positive from a
                # name collision
                merged = GrainTable(name, tables[0].kind, tables[0].bases)
                for t in tables:
                    for mn, gm in t.methods.items():
                        prev = merged.methods.get(mn)
                        if prev is None:
                            merged.methods[mn] = gm
                        else:
                            merged.methods[mn] = GrainMethod(
                                mn, min(prev.min_pos, gm.min_pos),
                                None if (prev.max_pos is None or
                                         gm.max_pos is None)
                                else max(prev.max_pos, gm.max_pos),
                                prev.kwonly | gm.kwonly,
                                prev.has_kwargs or gm.has_kwargs,
                                prev.one_way and gm.one_way)
            self.grains[name] = merged
        # monkey-patched methods (Class.attr = fn anywhere in the tree)
        # join as open unknown-arity entries BEFORE inheritance, so
        # subclasses see them too
        for m in modules:
            for cls, attr in m.grain_patches:
                tbl = self.grains.get(cls)
                if tbl is not None and not attr.startswith("_") and \
                        attr not in tbl.methods:
                    tbl.methods[attr] = GrainMethod(
                        attr, 0, None, frozenset(), True, False)
        # single-level-at-a-time base inheritance, to fixpoint
        for _ in range(4):
            changed = False
            for tbl in self.grains.values():
                for b in tbl.bases:
                    base = self.grains.get(b)
                    if base is None or base.kind != tbl.kind:
                        continue
                    for mn, gm in base.methods.items():
                        if mn not in tbl.methods:
                            tbl.methods[mn] = gm
                            changed = True
            if not changed:
                break

    # -- resolution ------------------------------------------------------
    def module_named(self, mod: str) -> ModuleSummary | None:
        """Module summary for an import-recorded module string: exact
        key first, else the unique dotted-suffix match."""
        hit = self.modules.get(mod)
        if hit is not None:
            return hit
        key = self._suffix_index.get(mod)
        return self.modules[key] if key is not None else None

    def enclosing_class(self, ms: ModuleSummary, qual: str) -> str | None:
        parts = qual.split(".")
        for p in parts[:-1]:
            if p in ms.classes:
                return p
        return None

    def resolve_call(self, ms: ModuleSummary, caller_qual: str,
                     chain: tuple) -> tuple | None:
        """CallEdge chain → (module_key, qualname) or None."""
        if not chain:
            return None
        local = resolve_local(ms, caller_qual, chain)
        if local is not None:
            return (ms.module_key, local)
        if len(chain) == 1:
            imp = ms.imports.get(chain[0])
            if imp is not None:
                mod, orig = imp
                target = self.module_named(mod)
                if target is not None and (orig or chain[0]) in \
                        target.functions:
                    return (target.module_key, orig or chain[0])
            return None
        if chain[0] in ("self", "cls") and len(chain) == 2:
            # unresolved locally: walk base classes by name
            cls = self.enclosing_class(ms, caller_qual)
            return self._method_on(cls, chain[1], seen=set()) \
                if cls else None
        # module-alias call: mod.func(...)
        if len(chain) == 2:
            imp = ms.imports.get(chain[0])
            if imp is not None and imp[1] == "":
                target = self.module_named(imp[0])
                if target is not None and chain[1] in target.functions:
                    return (target.module_key, chain[1])
        # typed receiver: resolve the receiver chain's class, then the
        # method on it (or its bases)
        recv = self.receiver_class(ms, caller_qual, chain[:-1])
        if recv is not None:
            return self._method_on(recv, chain[-1], seen=set())
        return None

    def _method_on(self, cls_name: str, meth: str,
                   seen: set) -> tuple | None:
        if cls_name in seen or len(seen) > 8:
            return None
        seen.add(cls_name)
        hit = self.class_index.get(cls_name)
        if hit is None:
            return None
        m, info = hit
        qual = f"{cls_name}.{meth}"
        if qual in m.functions:
            return (m.module_key, qual)
        for b in info.bases:
            found = self._method_on(b, meth, seen)
            if found is not None:
                return found
        return None

    def release_summary(self, key) -> FunctionSummary | None:
        """A function's summary with the link-time release overlay
        applied (the cached summary itself is never touched)."""
        s = self.functions.get(key)
        if s is None:
            return None
        ov = self._rel_overlay.get(key)
        if ov is None:
            return s
        return replace(s, releases=ov[0], returns_param=ov[1],
                       releases_items=ov[2])

    def extern_summary(self, ms: ModuleSummary, caller_qual: str):
        """Cross-module callee-summary lookup hook for the release
        walker (same signature as ``_call_releases``'s ``extern``).
        Resolves locals too and applies the release overlay, so
        check-time walks always see the freshest cross-module facts."""
        def look(chain):
            key = self.resolve_call(ms, caller_qual, chain)
            return self.release_summary(key) if key is not None else None
        return look

    def _release_overlay(self):
        """Cross-module transitive release closure: re-walk callers of
        releasing functions against the PROGRAM's resolution (overlay-
        aware), recording changed facts in ``_rel_overlay``. This is
        what closes the summary-cache staleness hole: the overlay is
        rebuilt from the current summaries on every link, so editing
        module A re-judges B's call edges into A while B's cached
        summary stays untouched."""
        work = {k for k in self.functions
                if self.functions[k].releases or
                self.functions[k].releases_items}
        for _ in range(6):
            if not work:
                break
            cands: set = set()
            for k in work:
                changed_callee = k in self._rel_overlay
                for gkey, _e in self._call_sites.get(k, []):
                    # same-module callers already saw the raw summary in
                    # the phase-1 closure; re-judge them only when the
                    # callee's facts CHANGED at link time
                    if changed_callee or gkey[0] != k[0]:
                        cands.add(gkey)
            work = set()
            for gkey in sorted(cands):
                mod, qual = gkey
                m = self.modules[mod]
                fn = m.fn_nodes.get(qual)
                if fn is None:
                    continue
                look = self.extern_summary(m, qual)
                rel, ret, _esc, items = _summarize_releases(
                    m, qual, fn, extern=look)
                cur = self.release_summary(gkey)
                if (rel, ret, items) != (cur.releases,
                                         cur.returns_param,
                                         cur.releases_items):
                    self._rel_overlay[gkey] = (rel, ret, items)
                    work.add(gkey)

    # -- type specs ------------------------------------------------------
    def resolve_spec(self, ms: ModuleSummary, fn: FunctionSummary | None,
                     spec, depth: int = 0):
        """spec → normal form ("cls", name) | ("dict", spec) | None."""
        if spec is None or depth > 10:
            return None
        tag = spec[0]
        if tag == "cls":
            return spec
        if tag == "dict":
            return spec
        if tag != "expr":
            return None
        _, base, steps = spec
        cur = None
        if base[0] == "self":
            cls = self.enclosing_class(ms, fn.qualname) if fn else None
            cur = ("cls", cls) if cls else None
        elif base[0] == "var":
            name = base[1]
            if fn is not None and name in fn.var_specs:
                sub = fn.var_specs[name]
                if sub != spec:  # self-reference guard
                    cur = self.resolve_spec(ms, fn, sub, depth + 1)
            if cur is None and name in ms.globals_specs:
                cur = self.resolve_spec(ms, None,
                                        ms.globals_specs[name], depth + 1)
            if cur is None and name in ms.imports:
                mod, orig = ms.imports[name]
                if orig and (orig in self.class_index):
                    cur = ("cls", orig)
                elif orig == "":
                    cur = ("mod", mod)
            if cur is None and name in ms.classes:
                cur = ("cls", name)
        elif base[0] == "spec":
            cur = self.resolve_spec(ms, fn, base[1], depth + 1)
        for step in steps:
            if cur is None:
                return None
            cur = self._apply_step(cur, step, depth)
        return cur

    def _apply_step(self, cur, step, depth):
        kind = step[0]
        if cur[0] == "mod" and kind == "attr":
            target = self.module_named(cur[1])
            if target is None:
                return None
            if step[1] in target.classes:
                return ("cls", step[1])
            sub = target.globals_specs.get(step[1])
            return self.resolve_spec(target, None, sub, depth + 1) \
                if sub is not None else None
        if kind == "attr":
            if cur[0] != "cls":
                return None
            hit = self.class_index.get(cur[1])
            if hit is None:
                return None
            m, info = hit
            sub = info.attr_specs.get(step[1])
            if sub is None:
                return None
            # class-level specs resolve in the CLASS's module, with
            # "self" meaning that class
            fake = FunctionSummary(f"{cur[1]}.__attr__", 0)
            return self.resolve_spec(m, fake, sub, depth + 1)
        if kind in ("sub", "dictval"):
            return cur[1] if cur[0] == "dict" else None
        if kind == "call":
            if cur[0] != "cls":
                return None
            hit = self.class_index.get(cur[1])
            if hit is None:
                return None
            m, info = hit
            ret = info.method_returns.get(step[1])
            if ret is None:
                return None
            fake = FunctionSummary(f"{cur[1]}.__ret__", 0)
            return self.resolve_spec(m, fake, ret, depth + 1)
        return None

    def receiver_class(self, ms: ModuleSummary, caller_qual: str,
                       recv_chain: tuple) -> str | None:
        """('self','ring') → 'SpscRing'-style receiver typing."""
        if not recv_chain:
            return None
        fn = ms.functions.get(caller_qual)
        if recv_chain[0] == "self":
            spec = ("expr", ("self",), tuple(
                ("attr", a) for a in recv_chain[1:]))
        else:
            spec = ("expr", ("var", recv_chain[0]), tuple(
                ("attr", a) for a in recv_chain[1:]))
        out = self.resolve_spec(ms, fn, spec)
        return out[1] if out is not None and out[0] == "cls" else None

    # -- worker-context fixpoint ----------------------------------------
    def loop_kind(self, ms: ModuleSummary, caller_qual: str,
                  loop_chain: tuple) -> str | None:
        """'worker' | 'main' | None for the receiver of a loop-callback
        registration."""
        if not loop_chain:
            return None
        fn = ms.functions.get(caller_qual)
        # direct: self.<attr> where the enclosing class assigned the
        # attr from new_event_loop()/get_running_loop()
        if loop_chain[0] in ("self", "cls") and len(loop_chain) == 2:
            cls = self.enclosing_class(ms, caller_qual)
            if cls:
                hit = self.class_index.get(cls)
                if hit is not None:
                    kind = hit[1].loop_attrs.get(loop_chain[1])
                    if kind is not None:
                        return kind
        # one alias hop: a local whose spec is a chain ending in a
        # loop-kind attr (loop = self.loop; pool.main_loop; ...)
        if fn is not None and len(loop_chain) == 1:
            spec = fn.var_specs.get(loop_chain[0])
            if spec is not None and spec[0] == "expr" and spec[2] and \
                    spec[2][-1][0] == "attr":
                attr = spec[2][-1][1]
                owner = self.resolve_spec(
                    ms, fn, ("expr", spec[1], spec[2][:-1]))
                if owner is not None and owner[0] == "cls":
                    hit = self.class_index.get(owner[1])
                    if hit is not None:
                        return hit[1].loop_attrs.get(attr)
                if spec[1][0] == "self" and len(spec[2]) == 1:
                    cls = self.enclosing_class(ms, caller_qual)
                    hit = self.class_index.get(cls) if cls else None
                    if hit is not None:
                        return hit[1].loop_attrs.get(attr)
        if len(loop_chain) == 2:
            owner = self.receiver_class(ms, caller_qual, loop_chain[:1])
            if owner is not None:
                hit = self.class_index.get(owner)
                if hit is not None:
                    return hit[1].loop_attrs.get(loop_chain[1])
        return None

    def _worker_fixpoint(self):
        work: list = []

        def mark(key, reason):
            if key is not None and key in self.functions and \
                    key not in self.worker:
                self.worker[key] = reason
                work.append(key)

        for m in self.modules.values():
            for name, info in m.classes.items():
                if info.is_thread:
                    mark((m.module_key, f"{name}.run"),
                         "Thread-subclass run()")
            for q, s in m.functions.items():
                for e in s.sched:
                    if e.kind == "thread":
                        mark(self.resolve_call(m, q, e.target),
                             "threading.Thread target")
                    elif e.kind == "executor":
                        mark(self.resolve_call(m, q, e.target),
                             "run_in_executor callable")
                    elif e.kind == "loop":
                        # affinity follows the LOOP's kind, not the
                        # scheduling caller's: the main loop handing a
                        # callback to a shard loop makes it worker code
                        if self.loop_kind(m, q, e.loop or ()) == \
                                "worker":
                            mark(self.resolve_call(m, q, e.target),
                                 "scheduled onto a shard/worker loop "
                                 f"in '{q.rsplit('.', 1)[-1]}'")
        self.worker_seeds = set(self.worker)
        while work:
            key = work.pop()
            mod, qual = key
            m = self.modules[mod]
            s = self.functions[key]
            short = qual.rsplit(".", 1)[-1]
            for e in s.calls:
                # a callable HANDED to a scheduler is not called here —
                # scheduling edges decide its affinity below
                if e.chain[-1] in _LOOP_CB_APIS or e.chain[-1] in (
                        "Thread", "run_in_executor", "create_task"):
                    continue
                mark(self.resolve_call(m, qual, e.chain),
                     f"called from worker context '{short}'")
            # sched edges need no re-scan here: thread/executor targets
            # and worker-loop callbacks were all seeded globally above
            # (loop affinity is a property of the loop, not the caller)

    # -- entry-point registry (link-time) --------------------------------
    def _collect_entry_contexts(self):
        """Consult the declared entry-point registry: naming
        conventions (``ctl_*``, ``receive_reminder``) plus the targets
        of loop/timer scheduling edges whose loop is NOT worker-kind
        (worker-loop callbacks belong to the worker fixpoint)."""
        for key in self.functions:
            label = entry_label_for_name(key[1])
            if label is not None:
                self.entry_contexts[key] = label
        for m in self.modules.values():
            for q, s in m.functions.items():
                for e in s.sched:
                    if e.kind not in ("loop", "timer"):
                        continue
                    if e.kind == "loop" and \
                            self.loop_kind(m, q, e.loop or ()) == \
                            "worker":
                        continue
                    target = self.resolve_call(m, q, e.target)
                    if target is None:
                        continue
                    label = entry_label_for_sched(e.api, q)
                    if label is not None:
                        self.entry_contexts.setdefault(target, label)

    # -- per-call-edge context classification (k=1) ----------------------
    def worker_context(self, key) -> str | None:
        """None (not worker-reachable) | "seed" (a thread target /
        executor callable / worker-loop callback itself) | "only"
        (every call edge comes from worker context) | "mixed" (also
        reached from main-loop context or a declared entry point —
        judged per call edge, not at the definition)."""
        return self._worker_kind.get(key)

    def _classify_worker_contexts(self):
        for key in self.worker:
            if key in self.worker_seeds:
                self._worker_kind[key] = "seed"
                continue
            mixed = key in self.entry_contexts
            if not mixed:
                for gkey, _e in self._call_sites.get(key, []):
                    if gkey not in self.worker:
                        mixed = True
                        break
            self._worker_kind[key] = "mixed" if mixed else "only"

    # -- fence fixpoint --------------------------------------------------
    def fence_owner_class(self, name: str | None) -> bool:
        if name is None:
            return False
        hit = self.class_index.get(name)
        return hit is not None and hit[1].fence_owner

    def protected_accesses(self, ms: ModuleSummary,
                           s: FunctionSummary) -> list:
        """The subset of a function's recorded protected-attr accesses
        whose receiver actually resolves to a fence-owning class."""
        if not s.protected:
            return []
        if s.qualname.rsplit(".", 1)[-1] == "__init__":
            return []  # construction: single-threaded by definition
        out = []
        for p in s.protected:
            if p.recv == ("self",):
                cls = self.enclosing_class(ms, s.qualname)
                if self.fence_owner_class(cls):
                    out.append(p)
                continue
            cls = self.receiver_class(ms, s.qualname, p.recv)
            if self.fence_owner_class(cls):
                out.append(p)
        return out

    def _index_call_sites(self):
        for m in self.modules.values():
            for q, s in m.functions.items():
                for e in s.calls:
                    key = self.resolve_call(m, q, e.chain)
                    if key is not None:
                        self._call_sites.setdefault(key, []).append(
                            ((m.module_key, q), e))

    def call_sites(self, key) -> list:
        return self._call_sites.get(key, [])

    def _sccs(self) -> dict:
        """Condense the call graph (caller → callee) into strongly
        connected components; returns key → scc id. Iterative Tarjan —
        deep call chains must not hit the recursion limit."""
        adj: dict[tuple, list] = {k: [] for k in self.functions}
        for callee, sites in self._call_sites.items():
            for gkey, _e in sites:
                if gkey in adj:
                    adj[gkey].append(callee)
        index: dict[tuple, int] = {}
        low: dict[tuple, int] = {}
        on_stack: set = set()
        stack: list = []
        scc_of: dict[tuple, int] = {}
        counter = itertools.count()
        scc_counter = itertools.count()
        for root in adj:
            if root in index:
                continue
            work = [(root, 0)]
            while work:
                node, ci = work[-1]
                if ci == 0:
                    index[node] = low[node] = next(counter)
                    stack.append(node)
                    on_stack.add(node)
                recurse = False
                children = adj[node]
                while ci < len(children):
                    ch = children[ci]
                    ci += 1
                    if ch not in index:
                        work[-1] = (node, ci)
                        work.append((ch, 0))
                        recurse = True
                        break
                    if ch in on_stack:
                        low[node] = min(low[node], index[ch])
                if recurse:
                    continue
                work[-1] = (node, ci)
                if ci >= len(children):
                    work.pop()
                    if low[node] == index[node]:
                        sid = next(scc_counter)
                        while True:
                            w = stack.pop()
                            on_stack.discard(w)
                            scc_of[w] = sid
                            if w == node:
                                break
                    if work:
                        parent = work[-1][0]
                        low[parent] = min(low[parent], low[node])
        return scc_of

    def _fence_fixpoint(self):
        """held(F): every call site ENTERING F's call-graph cycle is
        lexically fenced or in a held caller. Roots (no known external
        call sites) are NOT held — an unfenced protected access there
        is a finding.

        Computed over the SCC condensation with LEAST-fixpoint
        promotion: within-cycle edges are ignored (a recursive helper's
        back edge inherits whatever its entry established), but a cycle
        cannot vouch for ITSELF — the optimistic per-function form let
        two unfenced mutually-recursive callers hide the exact bug
        class the rule gates, while a naive pessimistic form could
        never promote a fence-rooted recursive walk."""
        scc_of = self._sccs()
        entering: dict[int, list] = {}
        members: dict[int, int] = {}
        for key in self.functions:
            sid = scc_of[key]
            members[sid] = members.get(sid, 0) + 1
            entering.setdefault(sid, [])
        for callee, sites in self._call_sites.items():
            sid = scc_of.get(callee)
            if sid is None:
                continue
            for gkey, e in sites:
                if scc_of.get(gkey) != sid:
                    entering[sid].append((gkey, e))
        # a declared entry point is entered UNFENCED by the runtime
        # regardless of its visible call sites — its SCC can never be
        # promoted (the registry edge is a permanent unfenced entry)
        entry_sccs = {scc_of[k] for k in self.entry_contexts
                      if k in scc_of}
        held_scc: dict[int, bool] = {sid: False for sid in entering}
        changed = True
        guard = 0
        while changed and guard < 50:
            changed = False
            guard += 1
            for sid, edges in entering.items():
                if held_scc[sid] or not edges or sid in entry_sccs:
                    continue
                if all(e.fenced or held_scc.get(scc_of.get(gkey), False)
                       for gkey, e in edges):
                    held_scc[sid] = True
                    changed = True
        for key in self.functions:
            self.held[key] = held_scc.get(scc_of[key], False)

    def unfenced_witness(self, key) -> str | None:
        """A human-readable example of why a function is not fence-held
        (a declared entry context, one unfenced call site, or 'no call
        sites')."""
        label = self.entry_contexts.get(key)
        if label is not None:
            return f"entry point: {label}"
        sites = self._call_sites.get(key, [])
        if not sites:
            return "no fenced call path (entry point)"
        for gkey, e in sites:
            if not e.fenced and not self.held.get(gkey, False):
                return f"called unfenced from {gkey[1]} " \
                       f"({gkey[0].rsplit('.', 1)[-1]}.py:{e.lineno})"
        return None


def build_program(sources: "list[tuple[str, str, ast.Module | None]]"
                  ) -> Program:
    """[(source, rel_path, tree-or-None)] → linked Program. Files that
    do not parse contribute nothing (the engine reports them as
    OTPU000)."""
    mods = []
    for source, rel_path, tree in sources:
        try:
            mods.append(module_summary(source, rel_path, tree))
        except SyntaxError:
            continue
    return Program(mods)
