"""Checked-in finding baseline (the analyzer ratchet).

The baseline records accepted pre-existing findings by their
location-insensitive key (rule, path, symbol, message) with multiplicity,
so the gate fails only on NEW findings. Entries are written sorted, with
line numbers included for the human reader but ignored for matching —
unrelated edits above an accepted finding do not churn the gate.
"""

from __future__ import annotations

import json
from collections import Counter
from typing import Iterable, Sequence

from .model import Finding

__all__ = ["load_baseline", "write_baseline", "match_baseline"]

BASELINE_VERSION = 1


def _key_of(entry: dict) -> tuple:
    return (entry["rule"], entry["path"], entry.get("symbol", ""),
            entry["message"])


def load_baseline(path: str) -> Counter:
    """Baseline file → Counter of finding keys (missing file = empty:
    a fresh tree starts with an empty ratchet, not an error)."""
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return Counter()
    if not isinstance(data, dict) or "findings" not in data:
        raise ValueError(f"malformed baseline file {path!r}")
    return Counter(_key_of(e) for e in data["findings"])


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    entries = sorted((f.to_json() for f in findings),
                     key=lambda e: (e["path"], e["line"], e["col"],
                                    e["rule"], e["message"]))
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": BASELINE_VERSION,
                   "count": len(entries),
                   "findings": entries}, fh, indent=1, sort_keys=True)
        fh.write("\n")


def match_baseline(findings: Iterable[Finding],
                   baseline: Counter) -> tuple[list[Finding], Counter]:
    """Split findings into (new, stale-baseline-keys).

    Each baseline entry absorbs at most its multiplicity of matching
    findings; the leftover Counter names entries whose finding no longer
    exists (fixed code — prune them with ``--write-baseline``).
    """
    budget = Counter(baseline)
    new: list[Finding] = []
    for f in findings:
        if budget[f.key] > 0:
            budget[f.key] -= 1
        else:
            new.append(f)
    stale = Counter({k: v for k, v in budget.items() if v > 0})
    return new, stale
