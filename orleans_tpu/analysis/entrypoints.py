"""Declared entry-point registry consulted at link time (phase 2).

Some functions are invoked by the runtime without any visible call
site: ``ctl_*`` control handlers are dispatched by name over the
control socket, timer/reminder callbacks fire from the activation's
scheduler, ``call_soon_threadsafe`` targets are handed to a loop as
objects, and the multiproc tier registers ring-drain callbacks with
``loop.add_reader``. Before this registry existed the fence analysis
could only report the generic "no fenced call path (entry point)" for
them; worse, a function with SOME fenced call sites that was ALSO one
of these entry points could be promoted to fence-held even though the
runtime enters it unfenced.

The registry has two halves:

* **name patterns** — zero-call-site conventions recognised purely by
  the function's (qual)name: ``ctl_*`` handlers and the
  ``receive_reminder`` reminder hook.
* **scheduling APIs** — callables handed to a loop/timer registration
  API; phase 1 records these as :class:`SchedEdge`\\ s with the API
  name, and :class:`~.summaries.Program` asks this module for the
  declared context label at link time.

Both halves declare the entry as UNFENCED (the runtime never holds the
tick fence on behalf of an entry point) with main-loop affinity unless
the scheduling edge targets a worker-kind loop (that case stays with
the worker fixpoint, not this registry).
"""

from __future__ import annotations

import fnmatch

__all__ = ["entry_label_for_name", "entry_label_for_sched",
           "NAME_PATTERNS", "SCHED_API_LABELS"]

# (glob over the LAST qualname segment, human-readable context label)
NAME_PATTERNS: tuple[tuple[str, str], ...] = (
    ("ctl_*", "ctl_* control handler (dispatched by name, unfenced)"),
    ("receive_reminder",
     "reminder callback (fired by the reminder service, unfenced)"),
)

# scheduling API name → label template; ``{caller}`` is the short name
# of the function that registered the callback
SCHED_API_LABELS: dict[str, str] = {
    "call_soon_threadsafe":
        "call_soon_threadsafe target scheduled from '{caller}'",
    "call_soon": "loop callback scheduled from '{caller}'",
    "call_at": "timer callback scheduled from '{caller}'",
    "call_later": "timer callback scheduled from '{caller}'",
    "add_reader": "ring-drain/fd-ready callback registered by '{caller}'",
    "add_writer": "fd-writable callback registered by '{caller}'",
    "register_timer": "grain timer callback registered by '{caller}'",
}


def entry_label_for_name(qualname: str) -> str | None:
    """Declared context for a zero-call-site naming convention, or
    None. Matches the last dotted segment (``Silo.ctl_dump`` →
    ``ctl_dump``)."""
    short = qualname.rsplit(".", 1)[-1]
    for pat, label in NAME_PATTERNS:
        if fnmatch.fnmatchcase(short, pat):
            return label
    return None


def entry_label_for_sched(api: str, caller_qual: str) -> str | None:
    """Declared context for the target of a scheduling-API edge, or
    None when the API does not create a runtime entry point."""
    tpl = SCHED_API_LABELS.get(api)
    if tpl is None:
        return None
    return tpl.format(caller=caller_qual.rsplit(".", 1)[-1])
