"""CLI: ``python -m orleans_tpu.analysis [paths] [options]``.

Exit codes: 0 — no non-baselined findings; 1 — new findings (or parse
errors); 2 — usage/configuration error.
"""

from __future__ import annotations

import argparse
import json
import sys

from .baseline import load_baseline, match_baseline, write_baseline
from .engine import analyze_paths
from .model import RULES, all_rules

SEVERITY_ORDER = {"warning": 0, "error": 1}


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m orleans_tpu.analysis",
        description="Actor-invariant static analyzer (OTPU001-OTPU006).")
    parser.add_argument("paths", nargs="*", default=["orleans_tpu"],
                        help="files or directories to scan "
                             "(default: orleans_tpu)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--baseline", metavar="FILE",
                        help="accepted-findings file; only NEW findings "
                             "fail the run")
    parser.add_argument("--write-baseline", metavar="FILE",
                        help="write ALL current findings to FILE and "
                             "exit 0 (regenerates the ratchet)")
    parser.add_argument("--rules", metavar="IDS",
                        help="comma-separated rule ids to run "
                             "(default: all)")
    parser.add_argument("--min-severity", choices=("warning", "error"),
                        default="warning",
                        help="drop findings below this severity")
    args = parser.parse_args(argv)

    if args.write_baseline and (args.rules
                                or args.min_severity != "warning"):
        # a filtered write would silently DROP accepted findings outside
        # the filter from the ratchet, and the next full gate run would
        # report them as new — refuse rather than corrupt the baseline
        print("--write-baseline must run unfiltered (no --rules / "
              "--min-severity): the baseline is the full ratchet",
              file=sys.stderr)
        return 2

    rules = all_rules()
    if args.rules:
        wanted = {r.strip().upper() for r in args.rules.split(",")}
        unknown = wanted - set(RULES)
        if unknown:
            print(f"unknown rule ids: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [RULES[r] for r in sorted(wanted)]

    findings = analyze_paths(args.paths, rules=rules)
    floor = SEVERITY_ORDER[args.min_severity]
    findings = [f for f in findings
                if SEVERITY_ORDER.get(f.severity, 1) >= floor
                or f.rule == "OTPU000"]

    if args.write_baseline:
        write_baseline(args.write_baseline, findings)
        print(f"wrote {len(findings)} finding(s) to "
              f"{args.write_baseline}")
        return 0

    baseline = load_baseline(args.baseline) if args.baseline else None
    if baseline is not None:
        new, stale = match_baseline(findings, baseline)
        if args.rules or args.min_severity != "warning":
            # a filtered run cannot produce findings outside the filter,
            # so baseline entries for them are NOT evidence of fixed code
            # — reporting them stale would nudge the user toward churning
            # a correct ratchet
            stale = {}
    else:
        new, stale = findings, {}

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in new],
            "baselined": len(findings) - len(new),
            "stale_baseline": [list(k) for k in sorted(stale)],
        }, indent=1, sort_keys=True))
    else:
        for f in new:
            print(f.render())
        if stale:
            print(f"note: {sum(stale.values())} baseline entr"
                  f"{'y is' if sum(stale.values()) == 1 else 'ies are'} "
                  "stale (finding fixed) — regenerate with "
                  "--write-baseline", file=sys.stderr)
        summary = (f"{len(new)} new finding(s), "
                   f"{len(findings) - len(new)} baselined, "
                   f"{len({f.path for f in findings})} file(s) with "
                   "findings")
        print(summary if findings else "clean: no findings",
              file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
