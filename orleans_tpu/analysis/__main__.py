"""CLI: ``python -m orleans_tpu.analysis [paths] [options]``.

Exit codes: 0 — no non-baselined findings; 1 — new findings (or parse
errors); 2 — usage/configuration error.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

from .baseline import load_baseline, match_baseline, write_baseline
from .engine import analyze_paths
from .model import RULES, all_rules

SEVERITY_ORDER = {"warning": 0, "error": 1}


def _explain(rule_id: str) -> int:
    """``--explain OTPU007``: the rule's rationale plus the canonical
    bad/clean fixture pair, so a finding is self-documenting at the
    CLI without opening the docs."""
    rule_id = rule_id.strip().upper()
    all_rules()
    rule = RULES.get(rule_id)
    if rule is None:
        print(f"unknown rule id {rule_id!r} (known: "
              f"{', '.join(sorted(RULES))})", file=sys.stderr)
        return 2
    print(f"{rule.id} {rule.name} [{rule.severity}]")
    print(f"  {rule.description}\n")
    if rule.rationale:
        print("Why:")
        for line in rule.rationale.split(". "):
            line = line.strip().rstrip(".")
            if line:
                print(f"  {line}.")
        print()
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    fixtures = os.path.join(repo, "tests", "analysis_fixtures")
    shown = False
    for kind, label in (("bad", "Flagged (the canonical violation)"),
                        ("clean", "Clean (the sanctioned pattern)")):
        pats = [os.path.join(fixtures, f"{rule_id.lower()}_{kind}.py"),
                os.path.join(fixtures, "*",
                             f"{rule_id.lower()}_{kind}.py")]
        for pat in pats:
            for path in sorted(glob.glob(pat)):
                shown = True
                rel = os.path.relpath(path, repo)
                print(f"--- {label} — {rel} ---")
                with open(path, encoding="utf-8") as fh:
                    print(fh.read().rstrip())
                print()
                break
            else:
                continue
            break
    if not shown:
        print("(no fixture pair found beside this checkout — see "
              "tests/analysis_fixtures/ in the repository)")
    return 0


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m orleans_tpu.analysis",
        description="Actor-invariant static analyzer (OTPU001-OTPU010).",
        epilog="Exit codes: 0 — clean (no findings, or every finding "
               "matched the baseline / an inline suppression); 1 — at "
               "least one NEW finding or a file that does not parse "
               "(OTPU000); 2 — usage or configuration error (unknown "
               "rule id, filtered --write-baseline). Rule selection via "
               "--rules is deterministic: ids are sorted and resolved "
               "against the registry populated by importing every rule "
               "module, so rules added in new modules load the same way "
               "the built-ins do.")
    parser.add_argument("paths", nargs="*", default=["orleans_tpu"],
                        help="files or directories to scan "
                             "(default: orleans_tpu)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text")
    parser.add_argument("--baseline", metavar="FILE",
                        help="accepted-findings file; only NEW findings "
                             "fail the run")
    parser.add_argument("--write-baseline", metavar="FILE",
                        help="write ALL current findings to FILE and "
                             "exit 0 (regenerates the ratchet)")
    parser.add_argument("--rules", metavar="IDS",
                        help="comma-separated rule ids to run "
                             "(default: all)")
    parser.add_argument("--min-severity", choices=("warning", "error"),
                        default="warning",
                        help="drop findings below this severity")
    parser.add_argument("--intra-only", action="store_true",
                        help="legacy per-function configuration: no "
                             "summaries, no cross-function propagation, "
                             "program-backed rules (OTPU007-OTPU009) "
                             "disabled")
    parser.add_argument("--explain", metavar="RULE",
                        help="print a rule's rationale and its "
                             "canonical bad/clean fixture pair, then "
                             "exit")
    parser.add_argument("--stats", action="store_true",
                        help="print per-phase wall time and the "
                             "summary-cache hit ratio to stderr")
    args = parser.parse_args(argv)

    if args.explain:
        return _explain(args.explain)

    if args.write_baseline and (args.rules
                                or args.min_severity != "warning"
                                or args.intra_only):
        # a filtered write would silently DROP accepted findings outside
        # the filter from the ratchet, and the next full gate run would
        # report them as new — refuse rather than corrupt the baseline
        print("--write-baseline must run unfiltered (no --rules / "
              "--min-severity / --intra-only): the baseline is the "
              "full ratchet", file=sys.stderr)
        return 2

    rules = all_rules()
    if args.rules:
        wanted = {r.strip().upper() for r in args.rules.split(",")}
        unknown = wanted - set(RULES)
        if unknown:
            print(f"unknown rule ids: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [RULES[r] for r in sorted(wanted)]

    stats: "dict | None" = {} if args.stats else None
    suppressed: list = []
    findings = analyze_paths(args.paths, rules=rules,
                             interprocedural=not args.intra_only,
                             stats=stats, suppressed=suppressed)
    if stats is not None:
        total = sum(v for k, v in stats.items() if k.endswith("_s"))
        lookups = stats.get("cache_hits", 0) + \
            stats.get("cache_misses", 0)
        ratio = stats.get("cache_hits", 0) / lookups if lookups else 0.0
        print(f"stats: {stats.get('files', 0)} file(s) in "
              f"{total * 1000:.1f} ms — read+parse "
              f"{stats.get('read_parse_s', 0.0) * 1000:.1f} ms, "
              f"summarize {stats.get('summarize_s', 0.0) * 1000:.1f} ms"
              f" (cache {stats.get('cache_hits', 0)}/{lookups} hit, "
              f"{ratio:.0%}), link "
              f"{stats.get('link_s', 0.0) * 1000:.1f} ms, rules "
              f"{stats.get('rules_s', 0.0) * 1000:.1f} ms",
              file=sys.stderr)
    floor = SEVERITY_ORDER[args.min_severity]
    findings = [f for f in findings
                if SEVERITY_ORDER.get(f.severity, 1) >= floor
                or f.rule == "OTPU000"]

    if args.write_baseline:
        write_baseline(args.write_baseline, findings)
        print(f"wrote {len(findings)} finding(s) to "
              f"{args.write_baseline}")
        return 0

    baseline = load_baseline(args.baseline) if args.baseline else None
    if baseline is not None:
        new, stale = match_baseline(findings, baseline)
        if args.rules or args.min_severity != "warning" or \
                args.intra_only:
            # a filtered run cannot produce findings outside the filter,
            # so baseline entries for them are NOT evidence of fixed code
            # — reporting them stale would nudge the user toward churning
            # a correct ratchet
            stale = {}
    else:
        new, stale = findings, {}

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in new],
            "baselined": len(findings) - len(new),
            "stale_baseline": [list(k) for k in sorted(stale)],
        }, indent=1, sort_keys=True))
    elif args.format == "sarif":
        from .sarif import sarif_json
        new_set = {id(f) for f in new}
        baselined = [f for f in findings if id(f) not in new_set]
        print(sarif_json(new, suppressed=suppressed,
                         baselined=baselined,
                         baseline_path=args.baseline or
                         "analysis/baseline.json"))
    else:
        for f in new:
            print(f.render())
        if stale:
            print(f"note: {sum(stale.values())} baseline entr"
                  f"{'y is' if sum(stale.values()) == 1 else 'ies are'} "
                  "stale (finding fixed) — regenerate with "
                  "--write-baseline", file=sys.stderr)
        summary = (f"{len(new)} new finding(s), "
                   f"{len(findings) - len(new)} baselined, "
                   f"{len({f.path for f in findings})} file(s) with "
                   "findings")
        print(summary if findings else "clean: no findings",
              file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
