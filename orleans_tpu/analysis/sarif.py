"""SARIF 2.1.0 output — findings as CI-renderable annotations.

Minimal but valid static-analysis interchange: one run, one driver, the
rule metadata from the registry, one result per finding. GitHub code
scanning and most CI viewers render these as inline annotations at the
exact line/column the text format prints.

Suppressed findings are EMITTED, not omitted: an inline
``# otpu: ignore`` marker becomes a result with an ``inSource``
suppression, a baseline match becomes an ``external`` one (justified by
the ratchet file). Dashboards can therefore trend suppression debt —
an omitted finding looks identical to a fixed one, which is exactly the
signal loss the ratchet exists to prevent.
"""

from __future__ import annotations

import json
from typing import Iterable, Sequence

from .model import RULES, Finding, all_rules

__all__ = ["to_sarif", "sarif_json"]

_LEVELS = {"error": "error", "warning": "warning"}

SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
          "master/Schemata/sarif-schema-2.1.0.json")


def to_sarif(findings: Sequence[Finding], *,
             suppressed: Sequence[Finding] = (),
             baselined: Sequence[Finding] = (),
             baseline_path: str = "analysis/baseline.json",
             tool_version: str = "1.0") -> dict:
    all_rules()  # ensure the registry is populated
    rule_ids = sorted({f.rule for f in (*findings, *suppressed,
                                        *baselined)} | set(RULES))
    rules_meta = []
    for rid in rule_ids:
        rule = RULES.get(rid)
        meta = {"id": rid}
        if rule is not None:
            meta["name"] = rule.name
            meta["shortDescription"] = {"text": rule.description}
            if rule.rationale:
                meta["fullDescription"] = {"text": rule.rationale}
            meta["defaultConfiguration"] = {
                "level": _LEVELS.get(rule.severity, "warning")}
        else:  # OTPU000 parse errors carry no registered rule
            meta["shortDescription"] = {"text": "file does not parse"}
            meta["defaultConfiguration"] = {"level": "error"}
        rules_meta.append(meta)
    index = {rid: i for i, rid in enumerate(rule_ids)}

    def result(f: Finding, suppressions: "list | None") -> dict:
        out = {
            "ruleId": f.rule,
            "ruleIndex": index[f.rule],
            "level": _LEVELS.get(f.severity, "warning"),
            "message": {"text": f.message +
                        (f" [{f.symbol}]" if f.symbol else "")},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(f.line, 1),
                               "startColumn": max(f.col, 1)},
                },
                **({"logicalLocations": [{
                    "fullyQualifiedName": f.symbol}]}
                   if f.symbol else {}),
            }],
        }
        if suppressions is not None:
            out["suppressions"] = suppressions
        return out

    results = [result(f, None) for f in findings]
    results += [result(f, [{"kind": "inSource"}]) for f in suppressed]
    results += [result(f, [{"kind": "external",
                            "justification":
                                f"accepted in {baseline_path}"}])
                for f in baselined]

    return {
        "$schema": SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "orleans-tpu-analysis",
                "informationUri":
                    "https://github.com/rikbosch/orleans",
                "version": tool_version,
                "rules": rules_meta,
            }},
            "results": results,
            "columnKind": "unicodeCodePoints",
        }],
    }


def sarif_json(findings: Iterable[Finding], **kw) -> str:
    return json.dumps(to_sarif(list(findings), **kw), indent=1,
                      sort_keys=True)
