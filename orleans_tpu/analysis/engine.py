"""Analyzer driver: file discovery, parsing, suppression, rule dispatch.

Deterministic by construction — files are walked in sorted order and
findings are sorted (path, line, col, rule) — so the CLI output and the
baseline file diff cleanly across runs.
"""

from __future__ import annotations

import ast
import io
import os
import re
import time
import tokenize
from typing import Iterable, Sequence

from .model import RULES, FileContext, Finding, all_rules

__all__ = ["analyze_paths", "analyze_source", "iter_python_files",
           "suppressed_lines"]

# `# otpu: ignore` or `# otpu: ignore[OTPU001, OTPU003]`
_SUPPRESS_RE = re.compile(
    r"#\s*otpu:\s*ignore(?:\[([A-Za-z0-9_,\s]+)\])?")


def _comment_lines(source: str) -> list[tuple[int, str]]:
    """(line, comment-text) for every real comment token. Tokenizing —
    rather than regex-scanning raw lines — keeps a marker INSIDE a string
    literal from suppressing anything. Falls back to the raw-line scan
    only when the source does not tokenize (it then rarely parses either,
    so the fallback practically never decides a finding)."""
    try:
        return [(tok.start[0], tok.string)
                for tok in tokenize.generate_tokens(
                    io.StringIO(source).readline)
                if tok.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return [(i, line) for i, line in
                enumerate(source.splitlines(), start=1) if "#" in line]


def suppressed_lines(source: str) -> dict[int, frozenset[str] | None]:
    """Map line number → suppressed rule ids (None = all rules).

    A marker on a code line covers that line; a marker on a comment-only
    line covers the following line too (the idiomatic place when the code
    line is already long).
    """
    lines = source.splitlines()
    out: dict[int, frozenset | None] = {}
    for i, comment in _comment_lines(source):
        m = _SUPPRESS_RE.search(comment)
        if not m:
            continue
        rules = None
        if m.group(1):
            rules = frozenset(r.strip().upper()
                              for r in m.group(1).split(",") if r.strip())
        targets = [i]
        if i <= len(lines) and lines[i - 1].lstrip().startswith("#"):
            targets.append(i + 1)
        for t in targets:
            prev = out.get(t, frozenset())
            if prev is None or rules is None:
                out[t] = None
            else:
                out[t] = prev | rules
    return out


# simple (non-compound) statements: a marker anywhere on one covers the
# whole statement, so the natural end-of-line comment on a black-wrapped
# multi-line call still silences the finding anchored to its first line
_SIMPLE_STMTS = (ast.Expr, ast.Assign, ast.AugAssign, ast.AnnAssign,
                 ast.Return, ast.Raise, ast.Assert, ast.Delete)


def _spread_over_statements(supp: dict, tree: ast.Module) -> None:
    if not supp:
        return
    for stmt in ast.walk(tree):
        if not isinstance(stmt, _SIMPLE_STMTS):
            continue
        lo, hi = stmt.lineno, stmt.end_lineno or stmt.lineno
        if hi <= lo:
            continue
        marked = [supp[m] for m in range(lo, hi + 1) if m in supp]
        if not marked:
            continue
        rules = None if any(m is None for m in marked) else \
            frozenset().union(*marked)
        for line in range(lo, hi + 1):
            prev = supp.get(line, frozenset())
            supp[line] = None if (prev is None or rules is None) else \
                prev | rules


def _is_suppressed(f: Finding,
                   supp: dict[int, frozenset | None]) -> bool:
    rules = supp.get(f.line, frozenset())
    return rules is None or f.rule in rules


def analyze_source(source: str, rel_path: str, *,
                   rules: Iterable | None = None,
                   path: str | None = None,
                   program: "object | None" = None,
                   interprocedural: bool = True,
                   tree: "ast.Module | None" = None,
                   suppressed: "list[Finding] | None" = None
                   ) -> list[Finding]:
    """Run the (selected) rules over one source blob. Syntax errors come
    back as an ``OTPU000`` error finding rather than an exception — a
    file the analyzer cannot parse is a finding about that file.

    ``program`` is the linked cross-module summary index; when None and
    ``interprocedural`` is set, a single-module program is built from
    this source alone (helper + caller in one file still link).
    ``interprocedural=False`` reproduces the legacy per-function pass —
    no summaries, no call-site propagation, no program-backed rules.
    ``suppressed`` (optional list) collects the findings silenced by an
    inline ``# otpu: ignore`` marker instead of dropping them — SARIF
    reports them as ``suppressions`` so dashboards can trend the debt."""
    rel_path = rel_path.replace(os.sep, "/")
    if tree is None:
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            return [Finding("OTPU000", "error", rel_path, e.lineno or 0,
                            (e.offset or 0) or 1,
                            f"file does not parse: {e.msg}")]
    if program is None and interprocedural:
        from .summaries import build_program
        program = build_program([(source, rel_path, tree)])
    ctx = FileContext(path=path or rel_path, rel_path=rel_path,
                      source=source, tree=tree,
                      lines=source.splitlines(), program=program)
    supp = suppressed_lines(source)
    _spread_over_statements(supp, tree)
    findings: list[Finding] = []
    for rule in (rules if rules is not None else all_rules()):
        for f in rule.check(ctx):
            if _is_suppressed(f, supp):
                if suppressed is not None:
                    suppressed.append(f)
            else:
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def iter_python_files(paths: Sequence[str]) -> list[tuple[str, str]]:
    """Expand files/dirs into sorted (abs_path, rel_path) pairs. Relative
    paths are rooted at each argument's parent so ``orleans_tpu/runtime/x``
    stays stable regardless of the directory the CLI runs from."""
    out: list[tuple[str, str]] = []
    seen: set[str] = set()

    def add(full: str, rel: str) -> None:
        # overlapping CLI args (a dir and a file inside it) must not scan
        # a file twice — duplicates would double findings past their
        # baseline multiplicity and falsely fail the gate
        key = os.path.realpath(full)
        if key not in seen:
            seen.add(key)
            out.append((full, rel))

    for p in paths:
        p = p.rstrip("/")
        if os.path.isfile(p):
            # keep a relative CLI arg verbatim. An absolute one becomes
            # cwd-relative when possible, else keeps its full segment
            # chain (minus the root) — reducing to a basename would
            # silently disable path-scoped rules (OTPU006's dispatch/ops/
            # parallel check) and break baseline key matching
            rel = p
            if os.path.isabs(p):
                rel = os.path.relpath(p)
                if rel.startswith(".."):
                    rel = os.path.splitdrive(p)[1].lstrip(os.sep)
            add(p, rel)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__"
                                 and not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    full = os.path.join(dirpath, fn)
                    add(full, os.path.relpath(
                        full, os.path.dirname(p) or "."))
    out.sort(key=lambda t: t[1])
    return out


def analyze_paths(paths: Sequence[str], *,
                  rules: Iterable | None = None,
                  interprocedural: bool = True,
                  stats: "dict | None" = None,
                  suppressed: "list[Finding] | None" = None
                  ) -> list[Finding]:
    """Two-phase run: phase 1 summarizes every file (cached per content
    hash — see summaries.module_summary), phase 2 links them into one
    Program, then the rules run per file against the linked view. Files
    are parsed once and the tree shared between summary and rules.

    ``stats`` (optional dict) receives per-phase wall times in seconds
    (``read_parse_s``, ``summarize_s``, ``link_s``, ``rules_s``), the
    file count, and the phase-1 cache counters for this run
    (``cache_hits``/``cache_misses``). ``suppressed`` collects inline-
    suppressed findings (see ``analyze_source``)."""
    t0 = time.perf_counter()
    loaded: list[tuple[str, str, str, "ast.Module | None"]] = []
    for full, rel in iter_python_files(paths):
        with open(full, encoding="utf-8") as fh:
            src = fh.read()
        try:
            tree = ast.parse(src)
        except SyntaxError:
            tree = None
        loaded.append((full, rel.replace(os.sep, "/"), src, tree))
    t1 = time.perf_counter()

    program = None
    t2 = t1
    if interprocedural:
        from .summaries import CACHE_STATS, Program, module_summary
        before = dict(CACHE_STATS)
        mods = []
        for _, rel, src, tree in loaded:
            if tree is None:
                continue
            try:
                mods.append(module_summary(src, rel, tree))
            except SyntaxError:
                continue
        t2 = time.perf_counter()
        program = Program(mods)
        if stats is not None:
            stats["cache_hits"] = CACHE_STATS["hits"] - before["hits"]
            stats["cache_misses"] = (CACHE_STATS["misses"] -
                                     before["misses"])
    t3 = time.perf_counter()

    findings: list[Finding] = []
    for full, rel, src, tree in loaded:
        findings.extend(analyze_source(
            src, rel, rules=rules, path=full, program=program,
            interprocedural=interprocedural, tree=tree,
            suppressed=suppressed))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    if stats is not None:
        stats["files"] = len(loaded)
        stats["read_parse_s"] = t1 - t0
        stats["summarize_s"] = t2 - t1
        stats["link_s"] = t3 - t2
        stats["rules_s"] = time.perf_counter() - t3
    return findings
