"""Finding/Rule model and the rule registry.

A Rule is a stateless checker over one parsed module; the registry maps
rule ids (``OTPU001``…) to singleton instances. Findings carry both an
exact location (path/line/col — what the CLI prints and fixtures assert)
and a location-insensitive identity (``key`` — what the baseline matches,
so accepted findings survive unrelated line churn above them).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Callable, Iterable, Iterator

__all__ = ["Finding", "Rule", "RULES", "register", "all_rules",
           "FileContext"]

SEVERITIES = ("error", "warning")


@dataclass(frozen=True)
class Finding:
    rule: str
    severity: str
    path: str          # posix-style path relative to the scan root
    line: int
    col: int
    message: str
    symbol: str = ""   # enclosing def/class qualname (baseline stability)

    @property
    def key(self) -> tuple:
        """Baseline identity: everything except line/col, so a finding
        accepted once is not re-reported when code above it moves."""
        return (self.rule, self.path, self.symbol, self.message)

    def to_json(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "path": self.path, "line": self.line, "col": self.col,
                "symbol": self.symbol, "message": self.message}

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} {self.severity}: {self.message}{sym}")


@dataclass
class FileContext:
    """Per-file inputs shared by every rule."""

    path: str                       # as given on the command line
    rel_path: str                   # posix, relative to the scan root
    source: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)
    # the linked cross-module summary index (summaries.Program), or None
    # when running the legacy intra-procedural configuration
    program: object | None = None

    @property
    def module(self):
        """This file's ModuleSummary inside ``program`` (or None)."""
        if self.program is None:
            return None
        return self.program.by_rel.get(self.rel_path)

    def finding(self, rule: "Rule", node: ast.AST, message: str,
                symbol: str = "") -> Finding:
        return Finding(rule.id, rule.severity, self.rel_path,
                       getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0) + 1,
                       message, symbol)


class Rule:
    """Base class: subclasses set ``id``/``name``/``severity`` and
    implement :meth:`check`."""

    id: str = ""
    name: str = ""
    severity: str = "error"
    description: str = ""
    # longer prose for ``--explain``: WHY the invariant exists and what
    # breaks when it is violated
    rationale: str = ""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError


RULES: dict[str, Rule] = {}


def register(cls: type[Rule]) -> type[Rule]:
    if not cls.id or cls.severity not in SEVERITIES:
        raise ValueError(f"bad rule class {cls!r}")
    if cls.id in RULES:
        raise ValueError(f"duplicate rule id {cls.id}")
    RULES[cls.id] = cls()
    return cls


def all_rules() -> list[Rule]:
    """Registered rules in id order (imports the rule modules on first
    use so the registry is populated lazily, not at package import)."""
    from . import rules  # noqa: F401 — registration side effect
    return [RULES[k] for k in sorted(RULES)]
