"""Actor-invariant static analyzer (the RoslynCodeGenerator/analyzer story
for a Python runtime).

Orleans keeps grain code inside the virtual-actor contract with compile-time
codegen and Roslyn analyzers; this package is the reproduction's equivalent:
a two-phase, summary-based interprocedural engine over ``orleans_tpu/``
that statically checks the invariants the hot lane (PR 3), the migration
fences (PR 9), and the multi-loop split (PR 11) made load-bearing.
Phase 1 summarizes each file independently (release/escape/alias
behavior per function, thread-affinity and scheduling edges, fence
state, registry writes, grain interface tables — cached per content
hash); phase 2 links the summaries into a program index the rules query
at call sites (``analysis.summaries``).

Rules
-----

========  ==========================================================
OTPU001   pool-discipline: pooled object used/stored after release,
          or released twice along one path — cross-function,
          alias-aware, loop-carried
OTPU002   blocking-in-turn: ``time.sleep`` / sync IO / ``.result()``
          inside an ``async def`` turn
OTPU003   interleaving-hazard: grain attribute written before and
          read after an ``await`` in a non-reentrant grain method
OTPU004   mutable-state-leak: grain method returns a shared mutable
          internal (``return self._rows``)
OTPU005   unawaited-grain-call: grain-ref coroutine dropped without
          an explicit fire-and-forget marker (``@one_way`` drops are
          recognized via the typed interface tables)
OTPU006   traced-impurity: function traced by ``jit``/``shard_map``/
          ``pjit`` captures or mutates host runtime state
OTPU007   loop-confinement: loop-confined registry (StatsRegistry/
          Histogram/QueueWaitTrend/SpanCollector/CallSiteStats)
          written from a worker-thread or ingress-shard context
          without the stamp-and-replay pattern
OTPU008   fence-discipline: donated device state (``.state`` /
          ``.hits`` on a fence-owning receiver) touched outside a
          held tick fence
OTPU009   grain-interface: ``get_grain``/``call_batch``/
          ``map_actors``/``broadcast_actors``/``join_when`` call site
          disagrees with the class's interface table (the Roslyn
          ``IncorrectGrainInterface`` analog)
========  ==========================================================

Usage::

    python -m orleans_tpu.analysis orleans_tpu/ \
        --baseline analysis/baseline.json

``--explain OTPU007`` prints a rule's rationale plus its canonical
bad/clean fixture pair; ``--format sarif`` emits SARIF 2.1.0 for CI
annotation rendering; ``--intra-only`` reproduces the legacy
per-function configuration (no summaries — OTPU007-009 disabled).

Suppress one finding in place with a trailing (or preceding full-line)
comment: ``# otpu: ignore[OTPU002]`` (rule list, or bare ``# otpu: ignore``
for all rules). Accepted pre-existing findings live in the checked-in
baseline; ``--write-baseline`` regenerates it (sorted, deterministic).
``tests/test_analysis.py`` runs the analyzer over the package as part of
tier-1, so any new finding fails CI until fixed, suppressed, or explicitly
baselined.
"""

from .baseline import load_baseline, match_baseline, write_baseline
from .engine import analyze_paths, analyze_source
from .model import RULES, Finding, Rule, all_rules
from .summaries import Program, build_program, module_summary

__all__ = [
    "Finding", "Program", "Rule", "RULES", "all_rules",
    "analyze_paths", "analyze_source", "build_program",
    "load_baseline", "match_baseline", "module_summary",
    "write_baseline",
]
