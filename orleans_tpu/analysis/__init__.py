"""Actor-invariant static analyzer (the RoslynCodeGenerator/analyzer story
for a Python runtime).

Orleans keeps grain code inside the virtual-actor contract with compile-time
codegen and Roslyn analyzers; this package is the reproduction's equivalent:
a stdlib-``ast`` lint pass over ``orleans_tpu/`` that statically checks the
invariants the hot lane (PR 3) and migration fences made load-bearing —
pool discipline for recycled ``Message``/``CallbackData`` shells, turn
discipline inside ``async def`` grain/runtime methods, and purity of
functions handed to ``jit``/``shard_map`` on the device tier.

Rules
-----

========  ==========================================================
OTPU001   pool-discipline: pooled object used/stored after release,
          or released twice along one path
OTPU002   blocking-in-turn: ``time.sleep`` / sync IO / ``.result()``
          inside an ``async def`` turn
OTPU003   interleaving-hazard: grain attribute written before and
          read after an ``await`` in a non-reentrant grain method
OTPU004   mutable-state-leak: grain method returns a shared mutable
          internal (``return self._rows``)
OTPU005   unawaited-grain-call: grain-ref coroutine dropped without
          an explicit fire-and-forget marker
OTPU006   traced-impurity: function traced by ``jit``/``shard_map``/
          ``pjit`` captures or mutates host runtime state
========  ==========================================================

Usage::

    python -m orleans_tpu.analysis orleans_tpu/ \
        --baseline analysis/baseline.json

Suppress one finding in place with a trailing (or preceding full-line)
comment: ``# otpu: ignore[OTPU002]`` (rule list, or bare ``# otpu: ignore``
for all rules). Accepted pre-existing findings live in the checked-in
baseline; ``--write-baseline`` regenerates it (sorted, deterministic).
``tests/test_analysis.py`` runs the analyzer over the package as part of
tier-1, so any new finding fails CI until fixed, suppressed, or explicitly
baselined.
"""

from .baseline import load_baseline, match_baseline, write_baseline
from .engine import analyze_paths, analyze_source
from .model import RULES, Finding, Rule, all_rules

__all__ = [
    "Finding", "Rule", "RULES", "all_rules",
    "analyze_paths", "analyze_source",
    "load_baseline", "match_baseline", "write_baseline",
]
