"""Ops entry points: the OrleansManager CLI analog.

Re-design of /root/reference/src/OrleansManager/Program.cs:62-94
(grainstats / collect / lookup / unregister / setcompatibilitystrategy /
fullgrainstats) as a library of async ops over a connected client, plus an
``python -m orleans_tpu.manage`` demo runner (the in-proc fabric has no
cross-process transport, so the CLI hosts a demo cluster to operate on;
deployments embed these ops next to their own client).
"""

from __future__ import annotations

import argparse
import asyncio
import json
from typing import Any

from .management import ManagementGrain

__all__ = ["grain_stats", "runtime_stats", "hosts", "collect",
           "debug_dump", "set_compatibility_strategy", "main"]


def _mgmt(client) -> Any:
    return client.get_grain(ManagementGrain, 0)


async def grain_stats(client) -> dict[str, int]:
    """`orleansmanager grainstats`: activations per grain class."""
    return await _mgmt(client).get_simple_grain_statistics()


async def runtime_stats(client) -> dict:
    return await _mgmt(client).get_runtime_statistics()


async def hosts(client) -> dict[str, str]:
    return await _mgmt(client).get_hosts()


async def collect(client, age_seconds: float = 0.0) -> int:
    """`orleansmanager collect`: force idle-activation collection."""
    return await _mgmt(client).force_activation_collection(age_seconds)


async def debug_dump(client) -> dict:
    return await _mgmt(client).get_debug_dump()


async def set_compatibility_strategy(client, compat: str | None = None,
                                     selector: str | None = None) -> None:
    await _mgmt(client).set_compatibility_strategy(compat, selector)


async def _demo(args) -> None:
    """Spin a demo cluster and run the requested op against it."""
    from .management import add_management
    from .runtime import ClusterClient, Grain, InProcFabric, SiloBuilder
    from .storage import MemoryStorage

    class DemoGrain(Grain):
        async def hello(self) -> str:
            return "hello"

    fabric = InProcFabric()
    storage = MemoryStorage()
    silos = []
    for i in range(args.silos):
        b = (SiloBuilder().with_name(f"demo{i}").with_fabric(fabric)
             .add_grains(DemoGrain).with_storage("Default", storage))
        add_management(b)
        silo = b.build()
        await silo.start()
        silos.append(silo)
    client = await ClusterClient(fabric).connect()
    for k in range(args.grains):
        await client.get_grain(DemoGrain, k).hello()

    op = {
        "grainstats": grain_stats, "runtimestats": runtime_stats,
        "hosts": hosts, "collect": collect, "dump": debug_dump,
    }[args.command]
    print(json.dumps(await op(client), indent=2, default=str))

    await client.close_async()
    for s in silos:
        await s.stop()


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(
        prog="orleans_tpu.manage",
        description="Cluster ops (OrleansManager analog) — demo runner")
    p.add_argument("command", choices=["grainstats", "runtimestats", "hosts",
                                       "collect", "dump"])
    p.add_argument("--silos", type=int, default=2)
    p.add_argument("--grains", type=int, default=10)
    args = p.parse_args(argv)
    asyncio.run(_demo(args))


if __name__ == "__main__":
    main()
