"""In-process multi-silo test clusters (reference L14,
src/Orleans.TestingHost/)."""

from .cluster import TestCluster, TestClusterBuilder

__all__ = ["TestCluster", "TestClusterBuilder"]
