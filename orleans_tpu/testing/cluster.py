"""TestCluster: N silos + client in one process, individually killable.

Re-design of /root/reference/src/Orleans.TestingHost/TestCluster.cs:29 +
TestClusterBuilder.cs:14: the reference isolates silos in AppDomains so they
can be killed/restarted independently (AppDomainSiloHandle.cs:14); here each
silo is an independent object on one event loop and "kill" drops it from
fabric routing with no goodbye (the same observable semantics: peers must
detect the death via the membership protocol).

Defaults: shared in-memory membership table with fast liveness config,
shared MemoryStorage, management installed. Reminders / streams /
transactions opt in via builder methods. ``kill_silo`` = ungraceful abort,
``restart_silo`` re-hosts the same endpoint with a higher generation,
``start_additional_silo`` grows the cluster — mirroring the TestCluster API
used across the reference's liveness tests
(test/Tester/MembershipTests/LivenessTests.cs:86-88).
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Callable

from ..core.ids import SiloAddress
from ..management import add_management
from ..membership import InMemoryMembershipTable, join_cluster
from ..runtime import ClusterClient, InProcFabric, SiloBuilder
from ..storage import MemoryStorage

__all__ = ["TestClusterBuilder", "TestCluster"]

FAST_LIVENESS = dict(
    membership_probe_period=0.1,
    membership_probe_timeout=0.15,
    membership_missed_probes_limit=2,
    membership_votes_needed=2,
    membership_iam_alive_period=0.5,
    membership_refresh_period=0.3,
    membership_vote_expiration=5.0,
    response_timeout=3.0,
)


class TestClusterBuilder:
    """Fluent cluster factory (TestClusterBuilder.cs:14)."""

    __test__ = False  # not a pytest collectible despite the name

    def __init__(self, n_silos: int = 2):
        self.n_silos = n_silos
        self.grains: list[type] = []
        self.storage: Any = None
        self.membership_table: Any = None
        self.with_membership = True
        self.with_management = True
        self.config: dict = dict(FAST_LIVENESS)
        self._silo_configurators: list[Callable[[SiloBuilder], Any]] = []

    def add_grains(self, *grain_classes: type) -> "TestClusterBuilder":
        self.grains.extend(grain_classes)
        return self

    def with_storage(self, storage) -> "TestClusterBuilder":
        self.storage = storage
        return self

    def with_config(self, **kw) -> "TestClusterBuilder":
        self.config.update(kw)
        return self

    def without_membership(self) -> "TestClusterBuilder":
        """Fabric-broadcast liveness only (no oracle) — fastest tests."""
        self.with_membership = False
        return self

    def with_reminders(self, table=None) -> "TestClusterBuilder":
        from ..reminders import InMemoryReminderTable, add_reminders
        table = table or InMemoryReminderTable()

        def cfg(b: SiloBuilder):
            b.configure(lambda silo: add_reminders_post(silo))

        # add_reminders must run pre-start but needs the silo object;
        # register through a builder configurator
        def add_reminders_post(silo):
            add_reminders(silo, table, refresh_period=0.2)

        self._silo_configurators.append(cfg)
        return self

    def with_sms_streams(self, name: str = "sms", **kw) -> "TestClusterBuilder":
        from ..streams import add_sms_streams
        self._silo_configurators.append(
            lambda b: add_sms_streams(b, name, **kw))
        return self

    def with_persistent_streams(self, name: str = "queue", adapter=None,
                                **kw) -> "TestClusterBuilder":
        from ..streams import MemoryQueueAdapter, add_persistent_streams
        adapter = adapter or MemoryQueueAdapter(n_queues=4)
        self._shared_adapter = adapter
        self._silo_configurators.append(
            lambda b: add_persistent_streams(b, name, adapter,
                                             pull_period=0.05, **kw))
        return self

    def with_transactions(self, log_provider=None,
                          shards: int | None = None) -> "TestClusterBuilder":
        from ..transactions import add_transactions
        kw = {}
        if log_provider is not None:
            kw["log_provider"] = log_provider
        if shards is not None:
            kw["shards"] = shards
        self._silo_configurators.append(
            lambda b: add_transactions(b, **kw))
        return self

    def with_metrics(self, sample_period: float = 0.1,
                     window: float = 60.0, *,
                     port: int | None = None,
                     otlp_endpoint: str | None = None,
                     otlp_period: float = 0.25) -> "TestClusterBuilder":
        """Live metrics pipeline on every silo (ingest stage
        instrumentation + queue/backpressure sampler; optionally the
        Prometheus endpoint — ``port=0`` binds ephemeral, read back from
        ``silo.metrics_server.port`` — and OTLP metrics push). Test-sized
        defaults: the sampler ticks fast enough for short tests to see
        windows fill."""
        cfg = dict(metrics_enabled=True,
                   metrics_sample_period=sample_period,
                   metrics_window=window)
        if port is not None:
            cfg["metrics_port"] = port
        if otlp_endpoint is not None:
            cfg["metrics_otlp_endpoint"] = otlp_endpoint
            cfg["metrics_otlp_period"] = otlp_period
        self.config.update(cfg)
        return self

    def with_slo(self, period: float = 0.05, fast_window: float = 0.3,
                 slow_window: float = 0.8, burn_threshold: float = 2.0,
                 min_events: int = 3, *,
                 latency_threshold: float | None = None,
                 latency_target: float | None = None,
                 shed_target: float | None = None) -> "TestClusterBuilder":
        """SLO engine on every silo (observability.slo.SloMonitor) with
        test-sized windows: the fast/slow burn windows fill within a
        sub-second drive so short tests see breaches detected and
        recovered. Implies metrics (the latency objectives read the
        ingest stage histograms); combine with ``with_profiling`` /
        ``with_tracing(tail=True)`` to exercise the full breach path."""
        cfg = dict(slo_enabled=True, slo_period=period,
                   slo_fast_window=fast_window,
                   slo_slow_window=slow_window,
                   slo_burn_threshold=burn_threshold,
                   slo_min_events=min_events)
        if latency_threshold is not None:
            cfg["slo_latency_threshold"] = latency_threshold
        if latency_target is not None:
            cfg["slo_latency_target"] = latency_target
        if shed_target is not None:
            cfg["slo_shed_target"] = shed_target
        if not self.config.get("metrics_enabled"):
            cfg.update(metrics_enabled=True, metrics_sample_period=0.1)
        self.config.update(cfg)
        return self

    def with_profiling(self, window: float = 0.1, ring: int = 120,
                       top_k: int = 8,
                       trigger_interval: float = 0.2
                       ) -> "TestClusterBuilder":
        """Host-loop occupancy profiler + flight recorder on every silo
        (observability.profiling.LoopProfiler). Test-sized defaults: the
        window rolls fast enough for short tests to see slices, and the
        trigger rate-limit is short enough that a forced anomaly
        snapshots promptly. Note: TestCluster silos share one event loop,
        so they share ONE profiler (occupancy is a loop property)."""
        self.config.update(profiling_enabled=True,
                           profiling_window=window,
                           profiling_ring=ring,
                           profiling_top_k=top_k,
                           profiling_trigger_interval=trigger_interval)
        return self

    def with_tracing(self, sample_rate: float = 1.0,
                     buffer_size: int = 4096, *, tail: bool = False,
                     tail_window: float = 0.25,
                     slow_threshold: float | None = None,
                     slow_percentile: float | None = None,
                     leg_ttl: float | None = None,
                     otlp_endpoint: str | None = None,
                     client: bool = True) -> "TestClusterBuilder":
        """Distributed request tracing on every silo AND the test client
        (the client is the root of most test traces); spans merge via
        ``TestCluster.trace_spans`` / ``export_trace``.

        ``tail=True`` enables tail-based retention everywhere: head
        sampling records, the keep/drop decision waits for trace
        completion (slow/errored/forced survive). ``client=False`` leaves
        the test client untraced so traces root silo-side (exercises the
        silo's own retention + cross-silo control-path pull)."""
        cfg = dict(trace_enabled=True, trace_sample_rate=sample_rate,
                   trace_buffer_size=buffer_size)
        if tail:
            cfg.update(trace_tail_enabled=True,
                       trace_tail_window=tail_window)
            if slow_threshold is not None:
                cfg["trace_tail_slow_threshold"] = slow_threshold
            if slow_percentile is not None:
                cfg["trace_tail_slow_percentile"] = slow_percentile
            if leg_ttl is not None:
                cfg["trace_tail_leg_ttl"] = leg_ttl
        if otlp_endpoint is not None:
            cfg["trace_otlp_endpoint"] = otlp_endpoint
        self.config.update(cfg)
        self._client_tracing = None
        if client:
            self._client_tracing = dict(
                sample_rate=sample_rate, buffer_size=buffer_size,
                tail=tail, tail_window=tail_window,
                slow_threshold=slow_threshold,
                slow_percentile=slow_percentile, leg_ttl=leg_ttl,
                otlp_endpoint=otlp_endpoint)
        return self

    def with_rebalancer(self, period: float = 0.2, budget: int | None = None,
                        imbalance_ratio: float | None = None
                        ) -> "TestClusterBuilder":
        """Live rebalancer on every silo (rebalance.add_rebalancer) with a
        test-fast round period."""
        from ..rebalance import add_rebalancer
        self._silo_configurators.append(
            lambda b: add_rebalancer(b, period=period, budget=budget,
                                     imbalance_ratio=imbalance_ratio))
        return self

    def with_vector_grains(self, *grain_classes: type,
                           **kw) -> "TestClusterBuilder":
        """Device-tier grains on every silo (dispatch.add_vector_grains):
        each test silo gets its own VectorRuntime on the CPU mesh; gateway
        affinity keeps one key's calls on one silo."""
        from ..dispatch import add_vector_grains
        self._silo_configurators.append(
            lambda b: add_vector_grains(b, *grain_classes, **kw))
        return self

    def configure_silo(self, fn: Callable[[SiloBuilder], Any]
                       ) -> "TestClusterBuilder":
        self._silo_configurators.append(fn)
        return self

    def build(self) -> "TestCluster":
        return TestCluster(self)


class TestCluster:
    """A deployed in-proc cluster (TestCluster.cs:29)."""

    __test__ = False  # not a pytest collectible despite the name

    def __init__(self, builder: TestClusterBuilder):
        self.builder = builder
        self.fabric = InProcFabric()
        self.storage = builder.storage or MemoryStorage()
        self.membership_table = (builder.membership_table
                                 or InMemoryMembershipTable())
        self.silos: list = []
        self.client: ClusterClient | None = None
        self._counter = 0

    # -- deployment ------------------------------------------------------
    async def deploy(self) -> "TestCluster":
        for _ in range(self.builder.n_silos):
            await self.start_additional_silo()
        self.client = await ClusterClient(self.fabric).connect()
        tracing = getattr(self.builder, "_client_tracing", None)
        if tracing is not None:
            if isinstance(tracing, tuple):  # legacy (rate, buffer) form
                self.client.enable_tracing(*tracing)
            else:
                self.client.enable_tracing(**tracing)
                if tracing.get("tail"):
                    # the testing-host analog of the silo's control-path
                    # retention pull (Silo._pull_trace_legs): the in-proc
                    # client pulls silo legs straight off their collectors
                    async def _fetch(tid: int) -> list[dict]:
                        out: list[dict] = []
                        for s in self.silos:
                            tr = getattr(s, "tracer", None)
                            if tr is not None and s.status == "Running":
                                out.extend(tr.pull(tid) if tr.tail
                                           else tr.snapshot(tid))
                        return out
                    self.client.tracer.remote_fetcher = _fetch
        if self.builder.with_membership:
            await self.wait_for_liveness()
        return self

    def _make_silo(self):
        i = self._counter
        self._counter += 1
        b = (SiloBuilder().with_name(f"silo{i}").with_fabric(self.fabric)
             .add_grains(*self.builder.grains)
             .with_storage("Default", self.storage)
             .with_config(**self.builder.config))
        if self.builder.with_management:
            add_management(b)
        for cfg in self.builder._silo_configurators:
            cfg(b)
        silo = b.build()
        if self.builder.with_membership:
            join_cluster(silo, self.membership_table)
        return silo

    async def start_additional_silo(self):
        """StartAdditionalSilo: elastic grow."""
        silo = self._make_silo()
        await silo.start()
        self.silos.append(silo)
        return silo

    # -- fault injection ---------------------------------------------------
    async def kill_silo(self, silo) -> None:
        """Abrupt death (KillSilo = AppDomain unload): no goodbye, no
        handoff; peers must detect via probes/votes."""
        await silo.stop(graceful=False)

    async def stop_silo(self, silo) -> None:
        """Graceful shutdown (StopSilo): goodbye row + directory handoff."""
        await silo.stop(graceful=True)

    async def restart_silo(self, silo):
        """RestartSilo: kill, then re-host the same endpoint with a higher
        generation (the membership prior-generation sweep must retire the
        old incarnation)."""
        endpoint = silo.silo_address
        if silo.status not in ("Stopped", "Dead"):
            await silo.stop(graceful=False)
        self.silos.remove(silo)
        reborn = self._make_silo()
        reborn.silo_address = SiloAddress(
            endpoint.host, endpoint.port, endpoint.generation + 1,
            endpoint.mesh_index)
        await reborn.start()
        self.silos.append(reborn)
        return reborn

    def partition(self, a, b) -> None:
        self.fabric.partition(a.silo_address, b.silo_address)

    def heal_partition(self, a, b) -> None:
        self.fabric.heal_partition(a.silo_address, b.silo_address)

    # -- access ------------------------------------------------------------
    def grain(self, grain_class: type, key, key_ext: str | None = None):
        return self.client.get_grain(grain_class, key, key_ext)

    # -- tracing ------------------------------------------------------------
    def trace_spans(self, trace_id: int | None = None) -> list[dict]:
        """Every span collected anywhere in the cluster (all silos + the
        test client), optionally filtered to one trace."""
        spans: list[dict] = []
        for s in self.silos:
            if getattr(s, "tracer", None) is not None:
                spans.extend(s.tracer.snapshot(trace_id))
        client_tracer = getattr(self.client, "tracer", None)
        if client_tracer is not None:
            spans.extend(client_tracer.snapshot(trace_id))
        return spans

    def clear_traces(self) -> None:
        for s in self.silos:
            if getattr(s, "tracer", None) is not None:
                s.tracer.clear()
        if getattr(self.client, "tracer", None) is not None:
            self.client.tracer.clear()

    async def drain_traces(self) -> None:
        """Deterministically settle tail retention everywhere, in two
        phases: first every collector decides its ROOTED traces (awaiting
        the cross-silo pulls those retentions trigger), then every
        collector expires whatever legs nobody pulled — expiring first
        would drop legs a peer's in-flight pull still needs. No-op for
        head-mode collectors."""
        collectors = []
        client_tracer = getattr(self.client, "tracer", None)
        if client_tracer is not None and client_tracer.tail:
            collectors.append(client_tracer)
        for s in self.silos:
            tr = getattr(s, "tracer", None)
            if tr is not None and tr.tail and s.status == "Running":
                collectors.append(tr)
        for tr in collectors:
            await tr.drain_tail(force=True, expire_legs=False)
        for tr in collectors:
            await tr.drain_tail(force=True)

    def retention_stats(self) -> dict:
        """Merged kept/dropped/... counters across client + silos (tests'
        quick view; the management surface is get_retention_stats)."""
        totals: dict[str, int] = {}
        collectors = [getattr(self.client, "tracer", None)] + \
            [getattr(s, "tracer", None) for s in self.silos]
        for tr in collectors:
            if tr is None:
                continue
            for k, v in tr.retention_stats().items():
                if isinstance(v, bool) or not isinstance(v, int):
                    continue
                totals[k] = totals.get(k, 0) + v
        return totals

    def export_trace(self, path: str, trace_id: int | None = None) -> str:
        """Merge spans from every silo + the client into one Chrome-trace/
        Perfetto JSON timeline file; returns ``path``."""
        from ..observability.export import write_chrome_trace
        return write_chrome_trace(path, self.trace_spans(trace_id))

    @property
    def alive_silos(self) -> list:
        return [s for s in self.silos if s.status == "Running"]

    # -- waiting helpers -----------------------------------------------------
    async def wait_until(self, cond: Callable[[], bool], timeout: float = 10.0,
                         msg: str = "condition") -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cond():
                return
            await asyncio.sleep(0.05)
        raise AssertionError(f"TestCluster: timed out waiting for {msg}")

    async def wait_for_liveness(self, timeout: float = 10.0) -> None:
        """Every running silo agrees on the active set."""
        def converged() -> bool:
            alive = self.alive_silos
            want = {s.silo_address for s in alive}
            return all(set(s.membership.active) == want for s in alive
                       if s.membership is not None)
        await self.wait_until(converged, timeout, "membership convergence")

    async def wait_for_death(self, silo, timeout: float = 10.0) -> None:
        await self.wait_until(
            lambda: all(silo.silo_address in s.membership.dead
                        for s in self.alive_silos
                        if s.membership is not None),
            timeout, f"death of {silo.silo_address}")

    # -- teardown ------------------------------------------------------------
    async def stop_all(self) -> None:
        if self.client is not None:
            tracer = getattr(self.client, "tracer", None)
            if tracer is not None:
                # settle sink flusher/pull tasks; tests that care about
                # exported spans drain_traces() explicitly before stopping
                await tracer.aclose(flush=False)
            await self.client.close_async()
            self.client = None
        for s in list(self.silos):
            if s.status not in ("Stopped", "Dead"):
                await s.stop()

    async def __aenter__(self) -> "TestCluster":
        return await self.deploy()

    async def __aexit__(self, *exc) -> None:
        await self.stop_all()
