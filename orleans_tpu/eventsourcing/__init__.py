"""Event sourcing / log-consistency (reference src/Orleans.EventSourcing/)."""

from .journaled import (
    CustomStorageAdaptor,
    JournaledGrain,
    LogStorageAdaptor,
    LogViewAdaptor,
    StateStorageAdaptor,
    log_consistency,
)

__all__ = [
    "JournaledGrain", "log_consistency", "LogViewAdaptor",
    "LogStorageAdaptor", "StateStorageAdaptor", "CustomStorageAdaptor",
]
