"""Event sourcing / log-consistency (reference src/Orleans.EventSourcing/)."""

from .journaled import (
    CustomStorageAdaptor,
    JournaledGrain,
    LogStorageAdaptor,
    LogViewAdaptor,
    StateStorageAdaptor,
    log_consistency,
    replicated_journal,
)

__all__ = [
    "JournaledGrain", "log_consistency", "replicated_journal",
    "LogViewAdaptor", "LogStorageAdaptor", "StateStorageAdaptor",
    "CustomStorageAdaptor",
]
