"""JournaledGrain: grain state as a fold over an event log.

Re-design of /root/reference/src/Orleans.EventSourcing/:
``JournaledGrain.cs:18,40`` (RaiseEvent/ConfirmEvents, TentativeState vs
confirmed State, TransitionState), the three ``ILogViewAdaptor`` providers —
``LogStorage/LogViewAdaptor.cs:389`` (full event log persisted),
``StateStorage/LogViewAdaptor.cs:362`` (snapshot + version),
``CustomStorage/LogViewAdaptor.cs:378`` (user-defined read/apply) — and the
CAS-retry write loop of ``Common/PrimaryBasedLogViewAdaptor.cs:907`` (on
etag conflict: reload the primary, replay pending entries, write again).
Multi-cluster notification tracking is a design hook (``notify``), not
implemented (SURVEY §2.4: geo replication out of minimum scope).
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Any

from ..core.errors import InconsistentStateError, OrleansError
from ..core.serialization import deep_copy
from ..runtime.grain import Grain

if TYPE_CHECKING:
    pass

log = logging.getLogger("orleans.eventsourcing")

__all__ = ["JournaledGrain", "log_consistency", "LogViewAdaptor",
           "LogStorageAdaptor", "StateStorageAdaptor", "CustomStorageAdaptor"]

MAX_WRITE_RETRIES = 16


class LogViewAdaptor:
    """Consistency-provider contract (ILogViewAdaptor): load the confirmed
    view, append confirmed events."""

    def __init__(self, storage_name: str = "Default"):
        self.storage_name = storage_name

    def _provider(self, grain: "JournaledGrain"):
        provider = grain._activation.runtime.storage_manager.get(
            self.storage_name)
        if provider is None:
            raise OrleansError(
                f"no storage provider {self.storage_name!r} for journal")
        return provider

    async def load(self, grain: "JournaledGrain") -> tuple[Any, int]:
        raise NotImplementedError

    async def append(self, grain: "JournaledGrain", events: list
                     ) -> tuple[Any, int]:
        """Persist ``events``; returns (new confirmed state, new version).
        Must be CAS-safe against concurrent writers (duplicate activation
        races): conflict → reload + replay + retry."""
        raise NotImplementedError

    def notify(self, grain: "JournaledGrain", events: list) -> None:
        """Multi-cluster notification hook (notification tracking in
        PrimaryBasedLogViewAdaptor) — no-op in single-cluster scope."""


class LogStorageAdaptor(LogViewAdaptor):
    """Persists the complete event log; the view is a fold."""

    def _key(self, grain) -> str:
        return f"journal-log:{type(grain).__name__}"

    async def load(self, grain):
        provider = self._provider(grain)
        data, etag = await provider.read(self._key(grain), grain.grain_id)
        grain.__journal_etag__ = etag
        events = data["log"] if data else []
        state = grain.initial_state()
        for e in events:
            state = grain.apply_event(state, e)
        return state, len(events)

    async def append(self, grain, events):
        provider = self._provider(grain)
        for _ in range(MAX_WRITE_RETRIES):
            data, etag = await provider.read(self._key(grain), grain.grain_id)
            logged = data["log"] if data else []
            try:
                new_etag = await provider.write(
                    self._key(grain), grain.grain_id,
                    {"log": logged + list(events)}, etag=etag)
            except InconsistentStateError:
                continue  # raced another writer: reload + retry
            grain.__journal_etag__ = new_etag
            state = grain.initial_state()
            for e in logged + list(events):
                state = grain.apply_event(state, e)
            return state, len(logged) + len(events)
        raise OrleansError("journal append: CAS retry exhausted")


class StateStorageAdaptor(LogViewAdaptor):
    """Persists (snapshot, version) only — events are not retained."""

    def _key(self, grain) -> str:
        return f"journal-state:{type(grain).__name__}"

    async def load(self, grain):
        provider = self._provider(grain)
        data, etag = await provider.read(self._key(grain), grain.grain_id)
        grain.__journal_etag__ = etag
        if data is None:
            return grain.initial_state(), 0
        return data["snapshot"], data["version"]

    async def append(self, grain, events):
        provider = self._provider(grain)
        for _ in range(MAX_WRITE_RETRIES):
            data, etag = await provider.read(self._key(grain), grain.grain_id)
            if data is None:
                state, version = grain.initial_state(), 0
            else:
                state, version = data["snapshot"], data["version"]
            for e in events:
                state = grain.apply_event(state, e)
            version += len(events)
            try:
                new_etag = await provider.write(
                    self._key(grain), grain.grain_id,
                    {"snapshot": state, "version": version}, etag=etag)
            except InconsistentStateError:
                continue
            grain.__journal_etag__ = new_etag
            return state, version
        raise OrleansError("journal snapshot write: CAS retry exhausted")


class CustomStorageAdaptor(LogViewAdaptor):
    """Delegates persistence to the grain (ICustomStorageInterface):
    ``read_state_from_storage() -> (state, version)`` and
    ``apply_updates_to_storage(events, expected_version) -> bool``."""

    async def load(self, grain):
        return await grain.read_state_from_storage()

    async def append(self, grain, events):
        for _ in range(MAX_WRITE_RETRIES):
            ok = await grain.apply_updates_to_storage(
                list(events), grain.version)
            if ok:
                state = grain._confirmed
                for e in events:
                    state = grain.apply_event(state, e)
                return state, grain.version + len(events)
            # version conflict: reload and retry on top of the new view
            state, version = await grain.read_state_from_storage()
            grain._confirmed, grain._version = state, version
        raise OrleansError("custom-storage append: retry exhausted")


_ADAPTORS = {
    "log_storage": LogStorageAdaptor,
    "state_storage": StateStorageAdaptor,
    "custom": CustomStorageAdaptor,
}


def log_consistency(provider: str, storage_name: str = "Default"):
    """Class decorator choosing the consistency provider
    ([LogConsistencyProvider] attribute analog)."""
    if provider not in _ADAPTORS:
        raise ValueError(f"unknown log-consistency provider {provider!r}; "
                         f"choose from {sorted(_ADAPTORS)}")

    def deco(cls: type) -> type:
        cls.__log_consistency__ = (provider, storage_name)
        return cls

    return deco


class JournaledGrain(Grain):
    """Event-sourced grain base (JournaledGrain<TState,TEvent>).

    Subclasses override ``initial_state()`` and ``apply_event(state, event)``
    (the TransitionState hook) and call ``raise_event``/``confirm_events``.
    """

    __log_consistency__ = ("log_storage", "Default")

    # -- user surface ----------------------------------------------------
    def initial_state(self) -> Any:
        return {}

    def apply_event(self, state: Any, event: Any) -> Any:
        """Default transition: events are dicts merged into a dict state
        (override for real domains)."""
        merged = dict(state)
        merged.update(event)
        return merged

    def raise_event(self, event: Any) -> None:
        """Queue an event (RaiseEvent): reflected in tentative_state now,
        durable after confirm_events."""
        self._pending.append(deep_copy(event))

    def raise_events(self, events: list) -> None:
        for e in events:
            self.raise_event(e)

    async def confirm_events(self) -> None:
        """Persist all pending events (ConfirmEvents)."""
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        try:
            state, version = await self._adaptor.append(self, batch)
        except BaseException:
            self._pending = batch + self._pending  # keep tentative view
            raise
        self._confirmed, self._version = state, version
        self._adaptor.notify(self, batch)

    @property
    def state(self) -> Any:
        """Confirmed view (State)."""
        return self._confirmed

    @property
    def tentative_state(self) -> Any:
        """Confirmed + unconfirmed events (TentativeState)."""
        s = deep_copy(self._confirmed)
        for e in self._pending:
            s = self.apply_event(s, e)
        return s

    @property
    def version(self) -> int:
        """Confirmed version = number of confirmed events."""
        return self._version

    @property
    def unconfirmed_events(self) -> list:
        return list(self._pending)

    async def refresh_now(self) -> None:
        """Re-read the confirmed view from storage (RetrieveConfirmedState)."""
        self._confirmed, self._version = await self._adaptor.load(self)

    # -- lifecycle -------------------------------------------------------
    async def on_activate(self) -> None:
        provider, storage_name = type(self).__log_consistency__
        self._adaptor = _ADAPTORS[provider](storage_name)
        self._pending: list = []
        self._confirmed, self._version = await self._adaptor.load(self)
