"""JournaledGrain: grain state as a fold over an event log.

Re-design of /root/reference/src/Orleans.EventSourcing/:
``JournaledGrain.cs:18,40`` (RaiseEvent/ConfirmEvents, TentativeState vs
confirmed State, TransitionState), the three ``ILogViewAdaptor`` providers —
``LogStorage/LogViewAdaptor.cs:389`` (full event log persisted),
``StateStorage/LogViewAdaptor.cs:362`` (snapshot + version),
``CustomStorage/LogViewAdaptor.cs:378`` (user-defined read/apply) — and the
CAS-retry write loop of ``Common/PrimaryBasedLogViewAdaptor.cs:907`` (on
etag conflict: reload the primary, replay pending entries, write again).

**Replication + notifications** (the notification-tracking half of
``PrimaryBasedLogViewAdaptor.cs:907``): a ``@replicated_journal`` grain
hosts one replica per silo (reads scale out; writes serialize through the
storage CAS). After a replica confirms events it broadcasts
``(from_version, events, new_version)`` to every peer silo's journal
notification target; receivers fold in-order notifications directly into
their confirmed view — no storage re-read — buffer out-of-order ones, and
catch up from storage only when a gap persists. Failed notification sends
are re-driven by a writer-side retry worker with backoff (the reference's
notification worker loop).
"""

from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING, Any

from ..core.errors import InconsistentStateError, OrleansError
from ..core.serialization import deep_copy
from ..runtime.grain import Grain

if TYPE_CHECKING:
    pass

log = logging.getLogger("orleans.eventsourcing")

__all__ = ["JournaledGrain", "log_consistency", "replicated_journal",
           "LogViewAdaptor", "LogStorageAdaptor", "StateStorageAdaptor",
           "CustomStorageAdaptor"]

MAX_WRITE_RETRIES = 16
# out-of-order notifications buffered before falling back to a storage read
MAX_NOTIFICATION_BUFFER = 64
# a version gap older than this triggers a storage catch-up even if the
# buffer is small (a dropped notification would otherwise stall the
# replica forever at low write rates)
GAP_CATCH_UP_DELAY = 1.0
NOTIFY_RETRIES = 3
NOTIFY_RETRY_BASE = 0.1
JOURNAL_NOTIFY_TARGET = "journal-notify"


class LogViewAdaptor:
    """Consistency-provider contract (ILogViewAdaptor): load the confirmed
    view, append confirmed events."""

    def __init__(self, storage_name: str = "Default"):
        self.storage_name = storage_name

    def _provider(self, grain: "JournaledGrain"):
        provider = grain._activation.runtime.storage_manager.get(
            self.storage_name)
        if provider is None:
            raise OrleansError(
                f"no storage provider {self.storage_name!r} for journal")
        return provider

    async def load(self, grain: "JournaledGrain") -> tuple[Any, int]:
        raise NotImplementedError

    async def append(self, grain: "JournaledGrain", events: list
                     ) -> tuple[Any, int]:
        """Persist ``events``; returns (new confirmed state, new version).
        Must be CAS-safe against concurrent writers (duplicate activation
        races): conflict → reload + replay + retry."""
        raise NotImplementedError

    def notify(self, grain: "JournaledGrain", events: list) -> None:
        """Multi-cluster notification hook (notification tracking in
        PrimaryBasedLogViewAdaptor) — no-op in single-cluster scope."""


class LogStorageAdaptor(LogViewAdaptor):
    """Persists the complete event log; the view is a fold."""

    def _key(self, grain) -> str:
        return f"journal-log:{type(grain).__name__}"

    async def load(self, grain):
        provider = self._provider(grain)
        data, etag = await provider.read(self._key(grain), grain.grain_id)
        grain.__journal_etag__ = etag
        events = data["log"] if data else []
        state = grain.initial_state()
        for e in events:
            state = grain.apply_event(state, e)
        return state, len(events)

    async def append(self, grain, events):
        provider = self._provider(grain)
        for _ in range(MAX_WRITE_RETRIES):
            data, etag = await provider.read(self._key(grain), grain.grain_id)
            logged = data["log"] if data else []
            try:
                new_etag = await provider.write(
                    self._key(grain), grain.grain_id,
                    {"log": logged + list(events)}, etag=etag)
            except InconsistentStateError:
                continue  # raced another writer: reload + retry
            grain.__journal_etag__ = new_etag
            state = grain.initial_state()
            for e in logged + list(events):
                state = grain.apply_event(state, e)
            return state, len(logged) + len(events)
        raise OrleansError("journal append: CAS retry exhausted")


class StateStorageAdaptor(LogViewAdaptor):
    """Persists (snapshot, version) only — events are not retained."""

    def _key(self, grain) -> str:
        return f"journal-state:{type(grain).__name__}"

    async def load(self, grain):
        provider = self._provider(grain)
        data, etag = await provider.read(self._key(grain), grain.grain_id)
        grain.__journal_etag__ = etag
        if data is None:
            return grain.initial_state(), 0
        return data["snapshot"], data["version"]

    async def append(self, grain, events):
        provider = self._provider(grain)
        for _ in range(MAX_WRITE_RETRIES):
            data, etag = await provider.read(self._key(grain), grain.grain_id)
            if data is None:
                state, version = grain.initial_state(), 0
            else:
                state, version = data["snapshot"], data["version"]
            for e in events:
                state = grain.apply_event(state, e)
            version += len(events)
            try:
                new_etag = await provider.write(
                    self._key(grain), grain.grain_id,
                    {"snapshot": state, "version": version}, etag=etag)
            except InconsistentStateError:
                continue
            grain.__journal_etag__ = new_etag
            return state, version
        raise OrleansError("journal snapshot write: CAS retry exhausted")


class CustomStorageAdaptor(LogViewAdaptor):
    """Delegates persistence to the grain (ICustomStorageInterface):
    ``read_state_from_storage() -> (state, version)`` and
    ``apply_updates_to_storage(events, expected_version) -> bool``."""

    async def load(self, grain):
        return await grain.read_state_from_storage()

    async def append(self, grain, events):
        for _ in range(MAX_WRITE_RETRIES):
            ok = await grain.apply_updates_to_storage(
                list(events), grain.version)
            if ok:
                state = grain._confirmed
                for e in events:
                    state = grain.apply_event(state, e)
                return state, grain.version + len(events)
            # version conflict: reload and retry on top of the new view
            state, version = await grain.read_state_from_storage()
            grain._confirmed, grain._version = state, version
        raise OrleansError("custom-storage append: retry exhausted")


_ADAPTORS = {
    "log_storage": LogStorageAdaptor,
    "state_storage": StateStorageAdaptor,
    "custom": CustomStorageAdaptor,
}


def replicated_journal(cls: type) -> type:
    """Class decorator: host one replica of this journaled grain per silo
    (stateless-worker placement, cap 1) and keep replicas converged via
    confirmed-event notifications instead of storage re-reads — the
    replica/notification model of PrimaryBasedLogViewAdaptor.cs:907
    applied across silos. Writes from any replica remain safe: the
    adaptors' CAS append serializes them through storage."""
    cls.__journal_replicated__ = True
    cls.__orleans_stateless_worker__ = 1  # one local replica per silo
    return cls


class JournalNotificationTarget:
    """Per-silo system target receiving confirmed-event notifications and
    folding them into local replicas as gated turns (the receiving half
    of the reference's notification tracking)."""

    def __init__(self, silo) -> None:
        self.silo = silo

    async def journal_notify(self, class_name: str, key, key_ext,
                             from_version: int, events: list,
                             new_version: int) -> bool:
        from ..core.ids import GrainId, GrainType
        gid = GrainId.for_grain(GrainType.of(class_name), key, key_ext)
        acts = self.silo.catalog.by_grain.get(gid)
        if not acts:
            return False   # no local replica: it will load from storage
        for act in list(acts):
            inst = act.grain_instance
            if isinstance(inst, JournaledGrain):
                # run as a gated turn so the fold never interleaves with
                # a half-finished grain turn on the same activation
                await self.silo.dispatcher.run_closed_turn(
                    act, lambda i=inst: i._fold_notification(
                        from_version, list(events), new_version))
        return True


def install_journal_notifier(silo) -> None:
    """Idempotently register the notification system target on a silo
    (called from Silo.start when a replicated journal class is hosted)."""
    if getattr(silo, "_journal_notifier", None) is None:
        silo._journal_notifier = JournalNotificationTarget(silo)
        silo.register_system_target(silo._journal_notifier,
                                    JOURNAL_NOTIFY_TARGET)


async def _notify_silo(silo, peer, class_name: str, key, key_ext,
                       from_version: int, events: list,
                       new_version: int) -> bool:
    """One journal_notify system-target call to ``peer`` (may be this
    silo). Shared by the intra-cluster broadcast and the geo relay."""
    from ..core.ids import GrainId, type_code_of
    from ..core.message import Category
    target = GrainId.system_target(
        type_code_of(JOURNAL_NOTIFY_TARGET), peer)
    return await silo.runtime_client.send_request(
        target_grain=target, grain_class=JournalNotificationTarget,
        interface_name="JournalNotificationTarget",
        method_name="journal_notify",
        args=(class_name, key, key_ext, from_version, list(events),
              new_version),
        kwargs={}, target_silo=peer, category=Category.SYSTEM)


class JournalRelayGrain(Grain):
    """Cross-cluster journal gateway (the ProtocolGateway analog,
    /root/reference/src/Orleans.Runtime/LogConsistency/ProtocolGateway.cs):
    a writer cluster pushes confirmed-event notifications to each remote
    cluster's relay grain over the cluster gateway; the relay fans them
    out to every silo of ITS cluster through the same notification target
    the intra-cluster broadcast uses. Keyed by the journaled grain's
    identity so relays for different grains parallelize."""

    async def journal_relay(self, class_name: str, key, key_ext,
                            from_version: int, events: list,
                            new_version: int) -> int:
        silo = self._activation.runtime
        peers = list(getattr(silo.locator, "alive_list", [])) or \
            [silo.silo_address]
        delivered = 0
        for peer in peers:
            try:
                if await _notify_silo(silo, peer, class_name, key, key_ext,
                                      from_version, events, new_version):
                    delivered += 1
            except Exception:  # noqa: BLE001 — a dying silo's replica
                # reloads from storage on next activation
                log.debug("journal relay to %s failed", peer, exc_info=True)
        return delivered


def log_consistency(provider: str, storage_name: str = "Default"):
    """Class decorator choosing the consistency provider
    ([LogConsistencyProvider] attribute analog)."""
    if provider not in _ADAPTORS:
        raise ValueError(f"unknown log-consistency provider {provider!r}; "
                         f"choose from {sorted(_ADAPTORS)}")

    def deco(cls: type) -> type:
        cls.__log_consistency__ = (provider, storage_name)
        return cls

    return deco


class JournaledGrain(Grain):
    """Event-sourced grain base (JournaledGrain<TState,TEvent>).

    Subclasses override ``initial_state()`` and ``apply_event(state, event)``
    (the TransitionState hook) and call ``raise_event``/``confirm_events``.
    """

    __log_consistency__ = ("log_storage", "Default")

    # -- user surface ----------------------------------------------------
    def initial_state(self) -> Any:
        return {}

    def apply_event(self, state: Any, event: Any) -> Any:
        """Default transition: events are dicts merged into a dict state
        (override for real domains)."""
        merged = dict(state)
        merged.update(event)
        return merged

    def raise_event(self, event: Any) -> None:
        """Queue an event (RaiseEvent): reflected in tentative_state now,
        durable after confirm_events."""
        self._pending.append(deep_copy(event))

    def raise_events(self, events: list) -> None:
        for e in events:
            self.raise_event(e)

    async def confirm_events(self) -> None:
        """Persist all pending events (ConfirmEvents)."""
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        try:
            state, version = await self._adaptor.append(self, batch)
        except BaseException:
            # deliberate post-await re-read: events raised DURING the
            # failed append must survive behind the restored batch — the
            # current value is wanted, not the pre-await one
            # otpu: ignore[OTPU003]
            self._pending = batch + self._pending  # keep tentative view
            raise
        self._confirmed, self._version = state, version
        self._adaptor.notify(self, batch)
        if getattr(type(self), "__journal_replicated__", False):
            self._broadcast_confirmed(batch, version)

    # -- replica notifications (PrimaryBasedLogViewAdaptor.cs:907) -------
    def _fold_notification(self, from_version: int, events: list,
                           new_version: int) -> None:
        """Apply a peer's confirmed events without re-reading storage:
        in-order → fold directly; out-of-order → buffer; persistent gap →
        schedule a storage catch-up."""
        if new_version <= self._version:
            return                       # duplicate / already seen
        self._notif_buffer[from_version] = (events, new_version)
        while self._version in self._notif_buffer:
            ev, nv = self._notif_buffer.pop(self._version)
            st = self._confirmed
            for e in ev:
                st = self.apply_event(st, e)
            self._confirmed, self._version = st, nv
        # prune buffered entries the fold has passed
        for fv in [v for v in self._notif_buffer if v < self._version]:
            self._notif_buffer.pop(fv, None)
        if len(self._notif_buffer) > MAX_NOTIFICATION_BUFFER:
            self._notif_buffer.clear()
            # a pending delayed catch-up would see the cleared buffer and
            # declare the gap healed — replace it with an immediate one
            if self._catch_up_task is not None and \
                    not self._catch_up_task.done():
                self._catch_up_task.cancel()
                self._catch_up_task = None
            self._schedule_catch_up(delay=0.0)
        elif self._notif_buffer:
            # a gap exists (a notification was lost or is late): if it
            # persists past GAP_CATCH_UP_DELAY, read storage — without
            # this a dropped notification stalls the replica forever at
            # low write rates
            self._schedule_catch_up(delay=GAP_CATCH_UP_DELAY)

    def _schedule_catch_up(self, delay: float) -> None:
        if self._catch_up_task is not None and not self._catch_up_task.done():
            return
        version_at_schedule = self._version
        act = self._activation

        async def catch_up() -> None:
            if delay:
                await asyncio.sleep(delay)
                if self._version > version_at_schedule or \
                        not self._notif_buffer:
                    return              # the gap healed on its own
            try:
                # run gated on the activation (like the fold) so the load
                # cannot interleave with a grain turn mid-await
                await act.runtime.dispatcher.run_closed_turn(
                    act, self.refresh_now)
            except Exception:  # noqa: BLE001
                log.exception("journal catch-up failed for %s",
                              self.grain_id)

        self._catch_up_task = asyncio.ensure_future(catch_up())

    def _broadcast_confirmed(self, batch: list, new_version: int) -> None:
        """Writer side: push (from_version, events, new_version) to every
        peer silo's notification target, and — when this silo is part of a
        multi-cluster deployment — to every known remote cluster's relay
        grain over the cluster gateways (geo replication: the
        notification-worker half of PrimaryBasedLogViewAdaptor.cs:907
        riding ProtocolGateway.cs). Failures retry with backoff; a cluster
        that stays unreachable catches up from primary storage via the
        replicas' gap machinery once notifications resume."""
        silo = self._activation.runtime
        from_version = new_version - len(batch)
        cname = type(self).__name__
        gid = self.grain_id
        peers = [s for s in getattr(silo.locator, "alive_list", [])
                 if s != silo.silo_address]

        async def notify_one(peer) -> None:
            for attempt in range(NOTIFY_RETRIES):
                try:
                    await _notify_silo(silo, peer, cname, gid.key,
                                       gid.key_ext, from_version,
                                       list(batch), new_version)
                    return
                except Exception:  # noqa: BLE001 — peer may be mid-death;
                    # its replica reloads from storage on next activation
                    await asyncio.sleep(NOTIFY_RETRY_BASE * (2 ** attempt))
            log.warning("journal notification to %s gave up for %s",
                        peer, gid)

        async def notify_cluster(cid: str) -> None:
            for attempt in range(NOTIFY_RETRIES):
                try:
                    client = await silo.gsi._client_for(cid)
                    relay = client.get_grain(
                        JournalRelayGrain, str(gid.key),
                        key_ext=f"{cname}|{gid.key_ext or ''}")
                    await relay.journal_relay(
                        cname, gid.key, gid.key_ext, from_version,
                        list(batch), new_version)
                    return
                except Exception:  # noqa: BLE001 — partition/restart: the
                    # remote replicas' gap catch-up reads primary storage
                    await asyncio.sleep(NOTIFY_RETRY_BASE * (2 ** attempt))
            log.warning("geo journal notification to cluster %s gave up "
                        "for %s", cid, gid)

        tasks = getattr(silo, "_journal_notify_tasks", None)
        if tasks is None:
            tasks = silo._journal_notify_tasks = set()

        def spawn(coro) -> None:
            t = asyncio.ensure_future(coro)
            tasks.add(t)
            t.add_done_callback(tasks.discard)

        for peer in peers:
            spawn(notify_one(peer))
        oracle = getattr(silo, "multicluster", None)
        if oracle is not None and getattr(silo, "gsi", None) is not None:
            for cid in oracle.known_clusters():
                if cid != oracle.cluster_id:
                    spawn(notify_cluster(cid))

    @property
    def state(self) -> Any:
        """Confirmed view (State)."""
        return self._confirmed

    @property
    def tentative_state(self) -> Any:
        """Confirmed + unconfirmed events (TentativeState)."""
        s = deep_copy(self._confirmed)
        for e in self._pending:
            s = self.apply_event(s, e)
        return s

    @property
    def version(self) -> int:
        """Confirmed version = number of confirmed events."""
        return self._version

    @property
    def unconfirmed_events(self) -> list:
        return list(self._pending)

    async def refresh_now(self) -> None:
        """Re-read the confirmed view from storage (RetrieveConfirmedState).
        The in-memory view only moves forward: CAS appends mean the stored
        version is monotone, so a load older than what we already confirmed
        (a read that raced a concurrent local append) is discarded."""
        state, version = await self._adaptor.load(self)
        if version > self._version:
            self._confirmed, self._version = state, version
            for fv in [v for v in self._notif_buffer if v < version]:
                self._notif_buffer.pop(fv, None)

    # -- lifecycle -------------------------------------------------------
    async def on_activate(self) -> None:
        provider, storage_name = type(self).__log_consistency__
        self._adaptor = _ADAPTORS[provider](storage_name)
        self._pending: list = []
        # out-of-order notification buffer: from_version → (events, new_v)
        self._notif_buffer: dict[int, tuple[list, int]] = {}
        self._catch_up_task: asyncio.Task | None = None
        self._confirmed, self._version = await self._adaptor.load(self)

    async def on_deactivate(self) -> None:
        if self._catch_up_task is not None:
            self._catch_up_task.cancel()
            self._catch_up_task = None
