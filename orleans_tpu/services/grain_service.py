"""GrainService: per-silo partitioned services with a ring range.

Re-design of /root/reference/src/Orleans.Core.Abstractions/Services/
IGrainService.cs + src/Orleans.Runtime/Services/ (GrainService base gets a
ring range; GrainServiceClient routes by key → range owner) and the
creation-from-config path (Silo.cs:566-595). The reminder service follows
the same pattern (LocalReminderService is the reference's canonical
GrainService).

A service instance runs on every silo as a system target named after its
class; ``owned_range``/``on_range_change`` track the one-point consistent
ring over the alive set. Clients route a key to the silo owning
``stable_hash64(key)`` and invoke the service method there.
"""

from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING

from ..core.ids import GrainId, SiloAddress, stable_hash64, type_code_of
from ..core.message import Category
from ..directory.ring import ConsistentRing, RingRange

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.services")

__all__ = ["GrainService", "GrainServiceClient", "add_grain_service"]


class GrainService:
    """Base class: subclass with public async methods; they become the
    remote service surface (like grain methods, pinned per-silo)."""

    _activation = None
    refresh_period = 1.0

    def __init__(self, silo: "Silo"):
        self.silo = silo
        self.ring = ConsistentRing(silo.locator.alive_list)
        self._range: RingRange | None = self.ring.my_range(silo.silo_address)
        self._task: asyncio.Task | None = None

    # -- lifecycle (wired by add_grain_service) --------------------------
    def start(self) -> None:
        if self.silo.membership is not None:
            self.silo.membership.subscribe(lambda a, d: self._update_ring())
        self._task = asyncio.get_running_loop().create_task(self._loop())
        self._update_ring()
        r = self.on_start()
        if asyncio.iscoroutine(r):
            asyncio.ensure_future(r)

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        r = self.on_stop()
        if asyncio.iscoroutine(r):
            asyncio.ensure_future(r)

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.refresh_period)
            self._update_ring()

    def _update_ring(self) -> None:
        self.ring.update(self.silo.locator.alive_list)
        new = self.ring.my_range(self.silo.silo_address)
        if new != self._range:
            old, self._range = self._range, new
            try:
                self.on_range_change(old, new)
            except Exception:  # noqa: BLE001
                log.exception("%s.on_range_change failed",
                              type(self).__name__)

    # -- overridables ----------------------------------------------------
    def on_start(self) -> None:  # noqa: B027
        pass

    def on_stop(self) -> None:  # noqa: B027
        pass

    def on_range_change(self, old: RingRange | None,
                        new: RingRange | None) -> None:  # noqa: B027
        """Partition moved (the reminder-reload analog)."""

    # -- helpers ---------------------------------------------------------
    @property
    def owned_range(self) -> RingRange | None:
        return self._range

    def owns_key(self, key) -> bool:
        r = self._range
        return r is not None and r.contains(stable_hash64(f"gsvc|{key}"))


class GrainServiceClient:
    """Routes service calls by key to the owning silo
    (GrainServiceClient<T> in the reference)."""

    def __init__(self, silo: "Silo", service_cls: type):
        self.silo = silo
        self.service_cls = service_cls
        self.name = service_cls.__name__

    def _owner(self, key) -> SiloAddress:
        ring = ConsistentRing(self.silo.locator.alive_list)
        owner = ring.owner(stable_hash64(f"gsvc|{key}"))
        return owner or self.silo.silo_address

    def call(self, key, method: str, *args, **kwargs):
        """Invoke ``method`` on the service instance owning ``key``."""
        owner = self._owner(key)
        gid = GrainId.system_target(type_code_of(self.name), owner)
        return self.silo.runtime_client.send_request(
            target_grain=gid, grain_class=self.service_cls,
            interface_name=self.name, method_name=method,
            args=args, kwargs=kwargs, target_silo=owner,
            category=Category.SYSTEM)


def add_grain_service(builder, service_cls: type, *factory_args):
    """Register a GrainService subclass on a SiloBuilder: one instance per
    silo, started at the grain-services lifecycle stage (Silo.cs:566-595)."""

    def install(silo) -> None:
        service = service_cls(silo, *factory_args)
        silo.register_system_target(service, service_cls.__name__)
        if not hasattr(silo, "grain_services"):
            silo.grain_services = {}
        silo.grain_services[service_cls.__name__] = service
        from ..runtime.silo import ServiceLifecycleStage
        silo.subscribe_lifecycle(
            ServiceLifecycleStage.RUNTIME_GRAIN_SERVICES,
            service.start, service.stop)

    return builder.configure(install)
