"""User-defined per-silo partitioned grain services (reference
src/Orleans.Runtime/Services/ + Core.Abstractions/Services/IGrainService.cs)."""

from .grain_service import GrainService, GrainServiceClient, add_grain_service

__all__ = ["GrainService", "GrainServiceClient", "add_grain_service"]
