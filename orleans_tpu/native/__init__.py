"""Native runtime components (C extensions).

The reference runs its serializer/transport hot path in compiled code
(codegen'd C# + IL emission, SerializationManager.cs:50,133); this package
holds the TPU build's native equivalents.  Components:

* ``_hotwire`` — wire-tier value codec (see ``hotwire.c``).
* ``_hotloop`` — per-callback runner for the host-loop occupancy
  profiler (see ``hotloop.c``).

Build strategy: compile-on-first-import into this directory with the
system toolchain (gcc/cc), guarded by a marker of the source hash so edits
rebuild automatically.  No setuptools ceremony, no install step; if the
toolchain or headers are missing the caller falls back to the pure-Python
path (``ORLEANS_TPU_NATIVE=0`` forces that fallback).
"""

from __future__ import annotations

import hashlib
import importlib.util
import logging
import os
import subprocess
import sysconfig
from pathlib import Path

log = logging.getLogger("orleans_tpu.native")

_DIR = Path(__file__).parent
_CACHED: dict[str, object] = {}


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


def _build(name: str, source: Path, tag: str) -> Path | None:
    """Compile ``source`` into ``<name>.<tag>.so`` beside it; returns the
    path or None on toolchain failure."""
    so = _DIR / f"{name}.{tag}.so"
    if so.exists():
        return so
    include = sysconfig.get_paths()["include"]
    cc = os.environ.get("CC", "gcc")
    # per-process tmp name: concurrent silo processes racing to build must
    # not interleave writes into one tmp file (os.replace itself is atomic)
    tmp = f"{so}.{os.getpid()}.tmp"
    cmd = [cc, "-O2", "-g0", "-fPIC", "-shared", "-fvisibility=hidden",
           f"-I{include}", str(source), "-o", tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        log.warning("native build unavailable (%s): %s", name, e)
        _unlink_quiet(tmp)
        return None
    if proc.returncode != 0:
        log.warning("native build failed (%s):\n%s", name, proc.stderr[-2000:])
        _unlink_quiet(tmp)
        return None
    os.replace(tmp, so)
    # retire stale builds of this module (old source hashes)
    for old in _DIR.glob(f"{name}.*.so"):
        if old != so:
            try:
                old.unlink()
            except OSError:
                pass
    return so


def load(name: str):
    """Load (building if needed) the native module ``name``; None if the
    environment can't build/load it."""
    if name in _CACHED:
        return _CACHED[name]
    mod = None
    if os.environ.get("ORLEANS_TPU_NATIVE", "1") != "0":
        source = _DIR / f"{name.lstrip('_')}.c"
        try:
            tag = hashlib.blake2b(source.read_bytes(),
                                  digest_size=8).hexdigest()
            so = _build(name, source, tag)
            if so is not None:
                spec = importlib.util.spec_from_file_location(
                    f"orleans_tpu.native.{name}", so)
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
        except Exception as e:  # noqa: BLE001 — never let native break import
            log.warning("native load failed (%s): %s", name, e)
            mod = None
    _CACHED[name] = mod
    return mod
