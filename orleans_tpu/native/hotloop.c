/* hotloop — native per-callback runner for the host-loop occupancy
 * profiler (observability.profiling.LoopProfiler).
 *
 * The profiler interposes on the event loop's call_soon/call_at and
 * times EVERY callback the loop runs.  In pure Python that prologue/
 * epilogue costs ~1.3us per callback (a Python frame, two
 * time.perf_counter calls, a contextvar read, two dict upserts, ~10
 * slot accesses) — measurable against the ~2.5us a trivial loop
 * callback costs at all.  This module is the same accounting as
 * LoopProfiler._run_cb/set_category compiled to C (~0.2-0.3us): the
 * loop schedules ONE Runner instance with the real callback as its
 * first argument, the Runner vectorcalls the callback between two
 * clock reads, and attributes the elapsed time into the shared
 * window-category dict.
 *
 * Division of labour: the Runner owns the HOT state (mark / last_end /
 * win_start / top_min / depth / closed scalars, the current category +
 * label, the open window's category->seconds dict) and exposes every
 * field as a writable member, so the Python LoopProfiler's slow paths
 * (window finalize, flight-recorder trigger, flush, enter/exit token
 * discipline) keep operating on the very same state through delegating
 * properties.  The two rare epilogue branches — top-K admission and
 * window finalize — call back into the Python profiler.
 *
 * Clock: CLOCK_MONOTONIC, the same base CPython uses for
 * time.perf_counter on Linux, so C-side stamps and Python-side stamps
 * interchange freely.
 *
 * Error discipline: accounting failures (OOM on a dict upsert) are
 * reported via PyErr_WriteUnraisable and never mask or corrupt the
 * wrapped callback's own result/exception; the callback's exception is
 * held across the epilogue's Python calls and re-raised unchanged.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <structmember.h>
#include <time.h>

static inline double mono_clock(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

/* interned keys / method names, set at module init */
static PyObject *s_idle, *s_other, *s_record_top, *s_finalize_window;

typedef struct {
    PyObject_HEAD
    vectorcallfunc vcall;
    double mark;        /* last attribution boundary */
    double last_end;    /* end of the previous callback (idle from) */
    double win_start;   /* current window start */
    double top_min;     /* top-K admission bar */
    double window;      /* seconds per occupancy slice */
    int depth;          /* >0 while inside a wrapped callback */
    int closed;         /* uninstalled: pass callbacks straight through */
    PyObject *cur;      /* category accruing since mark (str) */
    PyObject *cb_label; /* label for the top-K record, or None */
    PyObject *win_cats; /* dict: category -> seconds (open window) */
    PyObject *cat_var;  /* the LOOP_CATEGORY contextvar */
    PyObject *profiler; /* the owning LoopProfiler (slow paths) */
} Runner;

/* win_cats[key] += v (missing -> v).  Failures never propagate: the
 * callback's own outcome must not be masked by accounting. */
static void dict_add(PyObject *d, PyObject *key, double v) {
    if (d == NULL || key == NULL)
        return;
    PyObject *old = PyDict_GetItemWithError(d, key); /* borrowed */
    if (old == NULL && PyErr_Occurred())
        goto fail;
    if (old != NULL) {
        double prev = PyFloat_AsDouble(old);
        if (prev == -1.0 && PyErr_Occurred())
            goto fail;
        v += prev;
    }
    PyObject *f = PyFloat_FromDouble(v);
    if (f == NULL)
        goto fail;
    int rc = PyDict_SetItem(d, key, f);
    Py_DECREF(f);
    if (rc < 0)
        goto fail;
    return;
fail:
    PyErr_WriteUnraisable(d);
}

/* call profiler.<name>(...) with any pending exception preserved
 * (a2/a3 may be NULL — ObjArgs terminates at the first NULL) */
static void call_slow_path(Runner *r, PyObject *name, PyObject *a1,
                           PyObject *a2, PyObject *a3) {
    PyObject *exc_type, *exc_val, *exc_tb;
    PyErr_Fetch(&exc_type, &exc_val, &exc_tb);
    PyObject *res = PyObject_CallMethodObjArgs(r->profiler, name, a1, a2,
                                               a3, NULL);
    if (res == NULL)
        PyErr_WriteUnraisable(r->profiler);
    else
        Py_DECREF(res);
    PyErr_Restore(exc_type, exc_val, exc_tb);
}

static PyObject *runner_vectorcall(PyObject *self, PyObject *const *args,
                                   size_t nargsf, PyObject *kwnames) {
    Runner *r = (Runner *)self;
    Py_ssize_t nargs = PyVectorcall_NARGS(nargsf);
    if (nargs < 1 || (kwnames != NULL && PyTuple_GET_SIZE(kwnames) > 0)) {
        PyErr_SetString(PyExc_TypeError,
                        "Runner(callback, *args) takes a positional "
                        "callback and its positional arguments");
        return NULL;
    }
    PyObject *cb = args[0];
    if (r->closed)
        return PyObject_Vectorcall(cb, args + 1, nargs - 1, NULL);
    if (r->depth) {
        /* nested invocation (a wrapped fn called synchronously from
         * inside another): inner boundaries are a no-op */
        r->depth++;
        PyObject *res = PyObject_Vectorcall(cb, args + 1, nargs - 1, NULL);
        r->depth--;
        return res;
    }
    double now = mono_clock();
    double gap = now - r->last_end;
    if (gap > 0.0)
        /* the loop was in select() between callbacks: idle */
        dict_add(r->win_cats, s_idle, gap);
    r->depth = 1;
    r->mark = now;
    PyObject *cur;
    if (PyContextVar_Get(r->cat_var, s_other, &cur) < 0) {
        PyErr_WriteUnraisable(self);
        cur = Py_NewRef(s_other);
    }
    Py_XSETREF(r->cur, cur);                 /* owned */
    Py_XSETREF(r->cb_label, Py_NewRef(Py_None));

    PyObject *res = PyObject_Vectorcall(cb, args + 1, nargs - 1, NULL);

    /* hold the callback's exception across the whole epilogue: dict
     * lookups misread a pending exception as their own failure (and
     * would swallow it via PyErr_WriteUnraisable) */
    PyObject *exc_type = NULL, *exc_val = NULL, *exc_tb = NULL;
    if (res == NULL)
        PyErr_Fetch(&exc_type, &exc_val, &exc_tb);

    double end = mono_clock();
    r->depth = 0;
    double d = end - r->mark;
    if (d > 0.0)
        dict_add(r->win_cats, r->cur, d);
    r->last_end = end;
    if (end - now > r->top_min) {
        /* top-K slow-callback record (rare: the bar rises to the K-th
         * slowest as the window fills).  The third argument is the
         * callback's start offset WITHIN the open window, so the
         * Perfetto flame row places the record exactly instead of
         * laying durations end-to-end from the window start. */
        PyObject *dur = PyFloat_FromDouble(end - now);
        PyObject *off = PyFloat_FromDouble(now - r->win_start);
        if (dur != NULL && off != NULL)
            call_slow_path(r, s_record_top, cb, dur, off);
        Py_XDECREF(dur);
        Py_XDECREF(off);
    }
    if (end - r->win_start >= r->window) {
        PyObject *endf = PyFloat_FromDouble(end);
        if (endf != NULL) {
            call_slow_path(r, s_finalize_window, endf, NULL, NULL);
            Py_DECREF(endf);
        }
    }
    if (res == NULL)
        PyErr_Restore(exc_type, exc_val, exc_tb);
    return res; /* NULL propagates the callback's exception unchanged */
}

/* set_category(category, label=None): accrue to the current category up
 * to now, then switch — the engine segments one tick callback into
 * staging/transfer/sync slices with this, several times per tick. */
static PyObject *runner_set_category(PyObject *self, PyObject *const *args,
                                     Py_ssize_t nargs) {
    Runner *r = (Runner *)self;
    if (nargs < 1 || nargs > 2) {
        PyErr_SetString(PyExc_TypeError,
                        "set_category(category, label=None)");
        return NULL;
    }
    if (!r->depth || r->closed)
        Py_RETURN_NONE; /* outside a wrapped callback: no loop time */
    double now = mono_clock();
    double d = now - r->mark;
    if (d > 0.0)
        dict_add(r->win_cats, r->cur, d);
    r->mark = now;
    Py_XSETREF(r->cur, Py_NewRef(args[0]));
    if (nargs == 2 && args[1] != Py_None)
        Py_XSETREF(r->cb_label, Py_NewRef(args[1]));
    Py_RETURN_NONE;
}

static int runner_init(PyObject *self, PyObject *args, PyObject *kw) {
    Runner *r = (Runner *)self;
    PyObject *cat_var, *profiler;
    if (!PyArg_ParseTuple(args, "OO", &cat_var, &profiler))
        return -1;
    r->vcall = runner_vectorcall;
    double now = mono_clock();
    r->mark = r->last_end = r->win_start = now;
    r->top_min = 0.0;
    r->window = 1.0;
    r->depth = 0;
    r->closed = 0;
    Py_XSETREF(r->cur, Py_NewRef(s_other));
    Py_XSETREF(r->cb_label, Py_NewRef(Py_None));
    PyObject *d = PyDict_New();
    if (d == NULL)
        return -1;
    Py_XSETREF(r->win_cats, d);
    Py_XSETREF(r->cat_var, Py_NewRef(cat_var));
    Py_XSETREF(r->profiler, Py_NewRef(profiler));
    return 0;
}

static int runner_traverse(PyObject *self, visitproc visit, void *arg) {
    Runner *r = (Runner *)self;
    Py_VISIT(r->cur);
    Py_VISIT(r->cb_label);
    Py_VISIT(r->win_cats);
    Py_VISIT(r->cat_var);
    Py_VISIT(r->profiler);
    return 0;
}

static int runner_clear(PyObject *self) {
    Runner *r = (Runner *)self;
    Py_CLEAR(r->cur);
    Py_CLEAR(r->cb_label);
    Py_CLEAR(r->win_cats);
    Py_CLEAR(r->cat_var);
    Py_CLEAR(r->profiler);
    return 0;
}

static void runner_dealloc(PyObject *self) {
    PyObject_GC_UnTrack(self);
    runner_clear(self);
    Py_TYPE(self)->tp_free(self);
}

static PyMemberDef runner_members[] = {
    {"mark", T_DOUBLE, offsetof(Runner, mark), 0,
     "last attribution boundary (perf_counter base)"},
    {"last_end", T_DOUBLE, offsetof(Runner, last_end), 0,
     "end of the previous callback"},
    {"win_start", T_DOUBLE, offsetof(Runner, win_start), 0,
     "current window start"},
    {"top_min", T_DOUBLE, offsetof(Runner, top_min), 0,
     "top-K admission bar"},
    {"window", T_DOUBLE, offsetof(Runner, window), 0,
     "seconds per occupancy slice"},
    {"depth", T_INT, offsetof(Runner, depth), 0,
     ">0 while inside a wrapped callback"},
    {"closed", T_INT, offsetof(Runner, closed), 0,
     "uninstalled: callbacks pass straight through"},
    {"cur", T_OBJECT, offsetof(Runner, cur), 0,
     "category accruing since mark"},
    {"cb_label", T_OBJECT, offsetof(Runner, cb_label), 0,
     "top-K label for the current callback, or None"},
    {"win_cats", T_OBJECT, offsetof(Runner, win_cats), 0,
     "open window's category -> seconds dict"},
    {"cat_var", T_OBJECT, offsetof(Runner, cat_var), READONLY,
     "the LOOP_CATEGORY contextvar"},
    {NULL},
};

static PyMethodDef runner_methods[] = {
    {"set_category", (PyCFunction)(void (*)(void))runner_set_category,
     METH_FASTCALL,
     "set_category(category, label=None): accrue and switch the "
     "attribution category within the current callback."},
    {NULL, NULL, 0, NULL},
};

static PyTypeObject RunnerType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "_hotloop.Runner",
    .tp_basicsize = sizeof(Runner),
    .tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC |
                Py_TPFLAGS_HAVE_VECTORCALL,
    .tp_doc = "Native per-callback occupancy runner (see module doc).",
    .tp_new = PyType_GenericNew,
    .tp_init = runner_init,
    .tp_dealloc = runner_dealloc,
    .tp_traverse = runner_traverse,
    .tp_clear = runner_clear,
    .tp_call = PyVectorcall_Call,
    .tp_vectorcall_offset = offsetof(Runner, vcall),
    .tp_members = runner_members,
    .tp_methods = runner_methods,
};

static struct PyModuleDef hl_module = {
    PyModuleDef_HEAD_INIT, "_hotloop",
    "Native host-loop occupancy runner for orleans_tpu.", -1, NULL,
};

PyMODINIT_FUNC PyInit__hotloop(void) {
    s_idle = PyUnicode_InternFromString("idle");
    s_other = PyUnicode_InternFromString("other");
    s_record_top = PyUnicode_InternFromString("_record_top");
    s_finalize_window = PyUnicode_InternFromString("_finalize_window");
    if (!s_idle || !s_other || !s_record_top || !s_finalize_window)
        return NULL;
    if (PyType_Ready(&RunnerType) < 0)
        return NULL;
    PyObject *m = PyModule_Create(&hl_module);
    if (m == NULL)
        return NULL;
    Py_INCREF(&RunnerType);
    if (PyModule_AddObject(m, "Runner", (PyObject *)&RunnerType) < 0) {
        Py_DECREF(&RunnerType);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
