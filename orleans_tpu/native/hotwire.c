/* hotwire — native wire-tier codec for orleans_tpu (L1 wire serialization).
 *
 * Re-design of the reference's binary token-stream serializer
 * (/root/reference/src/Orleans.Core/Serialization/SerializationManager.cs:50,133
 * and BinaryTokenStreamWriter.cs) as a CPython C extension: a tagged
 * little-endian value codec specialized for the framework's message-header
 * types (GrainId / SiloAddress / ActivationId / ActivationAddress, scalars,
 * containers), with a per-value pickle escape hatch for anything else.
 *
 * Why native: the header tuple of every cross-process message rides this
 * codec.  The pickle path costs ~8us encode + ~12us decode per message
 * (restricted-unpickler find_class callbacks + reduce-protocol object
 * rebuilds); this codec does the same tuple in well under 1us each way and
 * removes pickle (and its attack surface) from the wire for all framework
 * types.  Bodies of scalars/arrays of scalars ride it too; arbitrary user
 * payloads fall back per-value to the configured (restricted) pickler.
 *
 * Wire format: [0xA7 magic][0x01 version][value]
 *   value := tag byte + payload (varint = unsigned LEB128; signed ints are
 *   zigzag-encoded).  Containers carry a count then nested values.  The
 *   id-type tags carry their fields positionally, including the precomputed
 *   64-bit uniform hash so decode never re-hashes.
 *
 * Safety: decode bounds-checks every read against the buffer, caps nesting
 * depth, and validates lengths before allocating.  Unknown tags and
 * truncated buffers raise ValueError — never crash, never read OOB.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>
#ifndef MS_WINDOWS
#include <errno.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <netinet/in.h>
#include <arpa/inet.h>
#include <unistd.h>
#endif

#define HW_MAGIC 0xA7
#define HW_VERSION 0x01
#define HW_MAX_DEPTH 200

/* value tags */
enum {
    T_NONE = 0x00,
    T_TRUE = 0x01,
    T_FALSE = 0x02,
    T_INT = 0x03,      /* zigzag varint, fits int64 */
    T_FLOAT = 0x05,    /* 8-byte IEEE754 little-endian */
    T_STR = 0x06,      /* varint len + utf8 */
    T_BYTES = 0x07,    /* varint len + raw */
    T_TUPLE = 0x08,    /* varint count + values */
    T_LIST = 0x09,
    T_DICT = 0x0A,     /* varint count + key,value pairs */
    T_SET = 0x0B,
    T_FROZENSET = 0x0C,
    T_GRAIN_ID = 0x0D,       /* category varint, type_code varint, key value,
                                key_ext value, hash64 varint */
    T_SILO_ADDR = 0x0E,      /* host value(str), port varint, generation varint,
                                mesh_index zigzag varint, uh varint */
    T_ACTIVATION_ID = 0x0F,  /* value varint */
    T_ACTIVATION_ADDR = 0x10,/* silo value, grain value, activation value */
    T_PICKLE = 0x11,   /* varint len + pickle bytes (restricted loader) */
};

/* ------------------------------------------------------------------ */
/* module state: configured Python types + helpers                     */

typedef struct {
    PyObject *grain_id_cls;
    PyObject *grain_cat_members; /* tuple indexed by category value */
    PyObject *silo_cls;
    PyObject *act_id_cls;
    PyObject *act_addr_cls;
    PyObject *pickle_dumps;      /* callable(obj) -> bytes */
    PyObject *pickle_loads;      /* callable(bytes) -> obj (restricted) */
    /* interned field-name strings for fast instance-dict fills */
    PyObject *s_category, *s_type_code, *s_key, *s_key_ext, *s_hash64;
    PyObject *s_host, *s_port, *s_generation, *s_mesh_index, *s_uh;
    PyObject *s_value, *s_silo, *s_grain, *s_activation;
    int configured;
    /* message-header struct spec (configure_headers): the field-name
     * tuple and enum restore spec cached module-side, so the per-message
     * socket path passes only (msg, ttl, body) — no Python-level spec
     * marshalling per frame. */
    PyObject *hdr_names;         /* tuple of str */
    PyObject *hdr_enum_spec;     /* tuple of (index, members) pairs */
    int hdr_configured;
} hw_state;

static hw_state g_state;  /* single-interpreter module; kept simple */

/* ------------------------------------------------------------------ */
/* growable write buffer                                               */

typedef struct {
    char *buf;
    Py_ssize_t len, cap;
} W;

static int w_init(W *w, Py_ssize_t cap) {
    w->buf = PyMem_Malloc(cap);
    if (!w->buf) { PyErr_NoMemory(); return -1; }
    w->len = 0; w->cap = cap;
    return 0;
}

static void w_free(W *w) { PyMem_Free(w->buf); w->buf = NULL; }

static int w_grow(W *w, Py_ssize_t need) {
    Py_ssize_t cap = w->cap;
    while (cap - w->len < need) cap += cap > (1<<20) ? (1<<20) : cap;
    char *nb = PyMem_Realloc(w->buf, cap);
    if (!nb) { PyErr_NoMemory(); return -1; }
    w->buf = nb; w->cap = cap;
    return 0;
}

static inline int w_byte(W *w, uint8_t b) {
    if (w->cap - w->len < 1 && w_grow(w, 1) < 0) return -1;
    w->buf[w->len++] = (char)b;
    return 0;
}

static inline int w_raw(W *w, const char *p, Py_ssize_t n) {
    if (w->cap - w->len < n && w_grow(w, n) < 0) return -1;
    memcpy(w->buf + w->len, p, n);
    w->len += n;
    return 0;
}

static int w_varint(W *w, uint64_t v) {
    uint8_t tmp[10]; int n = 0;
    do { uint8_t b = v & 0x7F; v >>= 7; if (v) b |= 0x80; tmp[n++] = b; } while (v);
    return w_raw(w, (char *)tmp, n);
}

static inline uint64_t zigzag(int64_t v) {
    return ((uint64_t)v << 1) ^ (uint64_t)(v >> 63);
}
static inline int64_t unzigzag(uint64_t v) {
    return (int64_t)(v >> 1) ^ -(int64_t)(v & 1);
}

/* ------------------------------------------------------------------ */
/* encoder                                                             */

static int enc_value(W *w, PyObject *obj, int depth);

/* Escape one value through the configured restricted pickler. */
static int enc_pickle(W *w, PyObject *obj) {
    if (!g_state.configured) {
        PyErr_SetString(PyExc_RuntimeError, "hotwire: not configured");
        return -1;
    }
    PyObject *data = PyObject_CallOneArg(g_state.pickle_dumps, obj);
    if (!data) return -1;
    char *p; Py_ssize_t n;
    if (PyBytes_AsStringAndSize(data, &p, &n) < 0) { Py_DECREF(data); return -1; }
    int rc = (w_byte(w, T_PICKLE) < 0 || w_varint(w, (uint64_t)n) < 0 ||
              w_raw(w, p, n) < 0) ? -1 : 0;
    Py_DECREF(data);
    return rc;
}

static int enc_str_payload(W *w, PyObject *s) {
    Py_ssize_t n;
    const char *p = PyUnicode_AsUTF8AndSize(s, &n);
    if (!p) return -1;
    if (w_varint(w, (uint64_t)n) < 0) return -1;
    return w_raw(w, p, n);
}

/* dig a field out of a (frozen-dataclass) instance */
static PyObject *get_field(PyObject *obj, PyObject *name) {
    return PyObject_GetAttr(obj, name);
}

static int enc_int_field(W *w, PyObject *obj, PyObject *name) {
    PyObject *v = get_field(obj, name);
    if (!v) return -1;
    int overflow = 0;
    long long ll = PyLong_AsLongLongAndOverflow(v, &overflow);
    Py_DECREF(v);
    if (overflow || (ll == -1 && PyErr_Occurred())) {
        if (!PyErr_Occurred())
            PyErr_SetString(PyExc_OverflowError, "id field exceeds int64");
        return -1;
    }
    return w_varint(w, zigzag(ll));
}

static int enc_obj_field(W *w, PyObject *obj, PyObject *name, int depth) {
    PyObject *v = get_field(obj, name);
    if (!v) return -1;
    int rc = enc_value(w, v, depth);
    Py_DECREF(v);
    return rc;
}

static int enc_value(W *w, PyObject *obj, int depth) {
    if (depth > HW_MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "hotwire: nesting too deep");
        return -1;
    }
    if (obj == Py_None) return w_byte(w, T_NONE);
    if (obj == Py_True) return w_byte(w, T_TRUE);
    if (obj == Py_False) return w_byte(w, T_FALSE);

    PyTypeObject *t = Py_TYPE(obj);

    if (t == &PyLong_Type) {
        int overflow = 0;
        long long ll = PyLong_AsLongLongAndOverflow(obj, &overflow);
        if (overflow) return enc_pickle(w, obj);  /* bignum: rare */
        if (ll == -1 && PyErr_Occurred()) return -1;
        if (w_byte(w, T_INT) < 0) return -1;
        return w_varint(w, zigzag(ll));
    }
    if (t == &PyFloat_Type) {
        double d = PyFloat_AS_DOUBLE(obj);
        uint64_t bits;
        memcpy(&bits, &d, 8);
#if PY_BIG_ENDIAN
        bits = __builtin_bswap64(bits);
#endif
        if (w_byte(w, T_FLOAT) < 0) return -1;
        return w_raw(w, (char *)&bits, 8);
    }
    if (t == &PyUnicode_Type) {
        Py_ssize_t n;
        const char *p = PyUnicode_AsUTF8AndSize(obj, &n);
        if (!p) {  /* lone surrogates etc: escape */
            PyErr_Clear();
            return enc_pickle(w, obj);
        }
        if (w_byte(w, T_STR) < 0 || w_varint(w, (uint64_t)n) < 0) return -1;
        return w_raw(w, p, n);
    }
    if (t == &PyBytes_Type) {
        char *p; Py_ssize_t n;
        PyBytes_AsStringAndSize(obj, &p, &n);
        if (w_byte(w, T_BYTES) < 0 || w_varint(w, (uint64_t)n) < 0) return -1;
        return w_raw(w, p, n);
    }
    if (t == &PyTuple_Type) {
        Py_ssize_t n = PyTuple_GET_SIZE(obj);
        if (w_byte(w, T_TUPLE) < 0) return -1;
        if (w_varint(w, (uint64_t)n) < 0) return -1;
        for (Py_ssize_t i = 0; i < n; i++) {
            /* tuples are immutable: items cannot move under us */
            if (enc_value(w, PyTuple_GET_ITEM(obj, i), depth + 1) < 0)
                return -1;
        }
        return 0;
    }
    if (t == &PyList_Type) {
        /* a nested pickle escape can run arbitrary __reduce__ code that
           mutates this list mid-encode: hold each item and re-check the
           size every step so we never read out of bounds, and reject the
           frame on mutation (the emitted count is already committed) */
        Py_ssize_t n = PyList_GET_SIZE(obj);
        if (w_byte(w, T_LIST) < 0) return -1;
        if (w_varint(w, (uint64_t)n) < 0) return -1;
        for (Py_ssize_t i = 0; i < n; i++) {
            if (PyList_GET_SIZE(obj) != n) {
                PyErr_SetString(PyExc_ValueError,
                                "hotwire: list mutated during encode");
                return -1;
            }
            PyObject *it = PyList_GET_ITEM(obj, i);
            Py_INCREF(it);
            int rc = enc_value(w, it, depth + 1);
            Py_DECREF(it);
            if (rc < 0) return -1;
        }
        return 0;
    }
    if (t == &PyDict_Type) {
        /* snapshot: PyDict_Next over a dict that a nested pickle escape
           resizes is undefined behavior */
        PyObject *items = PyDict_Items(obj);
        if (!items) return -1;
        Py_ssize_t n = PyList_GET_SIZE(items);
        if (w_byte(w, T_DICT) < 0 || w_varint(w, (uint64_t)n) < 0) {
            Py_DECREF(items);
            return -1;
        }
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *pair = PyList_GET_ITEM(items, i);
            if (enc_value(w, PyTuple_GET_ITEM(pair, 0), depth + 1) < 0 ||
                enc_value(w, PyTuple_GET_ITEM(pair, 1), depth + 1) < 0) {
                Py_DECREF(items);
                return -1;
            }
        }
        Py_DECREF(items);
        return 0;
    }
    if (t == &PySet_Type || t == &PyFrozenSet_Type) {
        if (w_byte(w, t == &PySet_Type ? T_SET : T_FROZENSET) < 0) return -1;
        if (w_varint(w, (uint64_t)PySet_GET_SIZE(obj)) < 0) return -1;
        PyObject *it = PyObject_GetIter(obj);
        if (!it) return -1;
        PyObject *item;
        while ((item = PyIter_Next(it))) {
            int rc = enc_value(w, item, depth + 1);
            Py_DECREF(item);
            if (rc < 0) { Py_DECREF(it); return -1; }
        }
        Py_DECREF(it);
        return PyErr_Occurred() ? -1 : 0;
    }

    if (g_state.configured) {
        if ((PyObject *)t == g_state.grain_id_cls) {
            if (w_byte(w, T_GRAIN_ID) < 0) return -1;
            if (enc_int_field(w, obj, g_state.s_category) < 0) return -1;
            if (enc_int_field(w, obj, g_state.s_type_code) < 0) return -1;
            if (enc_obj_field(w, obj, g_state.s_key, depth + 1) < 0) return -1;
            if (enc_obj_field(w, obj, g_state.s_key_ext, depth + 1) < 0) return -1;
            return enc_int_field(w, obj, g_state.s_hash64);
        }
        if ((PyObject *)t == g_state.silo_cls) {
            if (w_byte(w, T_SILO_ADDR) < 0) return -1;
            PyObject *host = get_field(obj, g_state.s_host);
            if (!host) return -1;
            int rc = enc_str_payload(w, host);
            Py_DECREF(host);
            if (rc < 0) return -1;
            if (enc_int_field(w, obj, g_state.s_port) < 0) return -1;
            if (enc_int_field(w, obj, g_state.s_generation) < 0) return -1;
            if (enc_int_field(w, obj, g_state.s_mesh_index) < 0) return -1;
            return enc_int_field(w, obj, g_state.s_uh);
        }
        if ((PyObject *)t == g_state.act_id_cls) {
            if (w_byte(w, T_ACTIVATION_ID) < 0) return -1;
            return enc_int_field(w, obj, g_state.s_value);
        }
        if ((PyObject *)t == g_state.act_addr_cls) {
            if (w_byte(w, T_ACTIVATION_ADDR) < 0) return -1;
            if (enc_obj_field(w, obj, g_state.s_silo, depth + 1) < 0) return -1;
            if (enc_obj_field(w, obj, g_state.s_grain, depth + 1) < 0) return -1;
            return enc_obj_field(w, obj, g_state.s_activation, depth + 1);
        }
    }
    /* anything else (enums, user dataclasses, exceptions, ndarrays):
       per-value restricted-pickle escape */
    return enc_pickle(w, obj);
}

/* ------------------------------------------------------------------ */
/* decoder                                                             */

typedef struct {
    const uint8_t *p, *end;
} R;

static int r_need(R *r, Py_ssize_t n) {
    if (r->end - r->p < n) {
        PyErr_SetString(PyExc_ValueError, "hotwire: truncated buffer");
        return -1;
    }
    return 0;
}

static int r_varint(R *r, uint64_t *out) {
    uint64_t v = 0; int shift = 0;
    while (1) {
        if (r_need(r, 1) < 0) return -1;
        uint8_t b = *r->p++;
        /* at shift 63 only the low payload bit fits in uint64; higher bits
           would silently truncate, so reject them too */
        if (shift >= 64 || (shift == 63 && (b & 0x7E))) {
            PyErr_SetString(PyExc_ValueError, "hotwire: varint overflow");
            return -1;
        }
        v |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
    }
    *out = v;
    return 0;
}

/* read a length varint and validate it against the remaining buffer;
   rejects values that would go negative when cast to Py_ssize_t */
static int r_len(R *r, Py_ssize_t *out) {
    uint64_t n;
    if (r_varint(r, &n) < 0) return -1;
    if (n > (uint64_t)(r->end - r->p)) {
        PyErr_SetString(PyExc_ValueError, "hotwire: truncated buffer");
        return -1;
    }
    *out = (Py_ssize_t)n;
    return 0;
}

static PyObject *dec_value(R *r, int depth);

static int dec_i64(R *r, int64_t *out) {
    uint64_t raw;
    if (r_varint(r, &raw) < 0) return -1;
    *out = unzigzag(raw);
    return 0;
}

/* build an instance of a plain Python class without running __init__:
   cls.__new__(cls), then fill fields via the generic attr machinery
   (bypasses the frozen-dataclass __setattr__ override by design). */
static PyObject *empty_args;  /* cached () for tp_new */

static PyObject *blank_instance(PyObject *cls) {
    return ((PyTypeObject *)cls)->tp_new((PyTypeObject *)cls, empty_args, NULL);
}

static int set_field(PyObject *inst, PyObject *name, PyObject *val) {
    /* val is stolen on success-or-failure for caller convenience */
    int rc = PyObject_GenericSetAttr(inst, name, val);
    Py_DECREF(val);
    return rc;
}

static int set_i64_field(PyObject *inst, PyObject *name, int64_t v) {
    PyObject *o = PyLong_FromLongLong(v);
    if (!o) return -1;
    return set_field(inst, name, o);
}

static PyObject *dec_value(R *r, int depth) {
    if (depth > HW_MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "hotwire: nesting too deep");
        return NULL;
    }
    if (r_need(r, 1) < 0) return NULL;
    uint8_t tag = *r->p++;
    switch (tag) {
    case T_NONE: Py_RETURN_NONE;
    case T_TRUE: Py_RETURN_TRUE;
    case T_FALSE: Py_RETURN_FALSE;
    case T_INT: {
        int64_t v;
        if (dec_i64(r, &v) < 0) return NULL;
        return PyLong_FromLongLong(v);
    }
    case T_FLOAT: {
        if (r_need(r, 8) < 0) return NULL;
        uint64_t bits;
        memcpy(&bits, r->p, 8);
        r->p += 8;
#if PY_BIG_ENDIAN
        bits = __builtin_bswap64(bits);
#endif
        double d;
        memcpy(&d, &bits, 8);
        return PyFloat_FromDouble(d);
    }
    case T_STR: {
        Py_ssize_t n;
        if (r_len(r, &n) < 0) return NULL;
        PyObject *s = PyUnicode_DecodeUTF8((const char *)r->p, n, NULL);
        if (s) r->p += n;
        return s;
    }
    case T_BYTES: {
        Py_ssize_t n;
        if (r_len(r, &n) < 0) return NULL;
        PyObject *b = PyBytes_FromStringAndSize((const char *)r->p, n);
        if (b) r->p += n;
        return b;
    }
    case T_TUPLE: case T_LIST: {
        /* each element takes >=1 byte, so r_len's remaining-buffer bound
           also caps the count before allocating */
        Py_ssize_t n;
        if (r_len(r, &n) < 0) return NULL;
        PyObject *c = tag == T_TUPLE ? PyTuple_New(n) : PyList_New(n);
        if (!c) return NULL;
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *v = dec_value(r, depth + 1);
            if (!v) { Py_DECREF(c); return NULL; }
            if (tag == T_TUPLE) PyTuple_SET_ITEM(c, i, v);
            else PyList_SET_ITEM(c, i, v);
        }
        return c;
    }
    case T_DICT: {
        Py_ssize_t n;
        if (r_len(r, &n) < 0) return NULL;
        PyObject *d = PyDict_New();
        if (!d) return NULL;
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *k = dec_value(r, depth + 1);
            if (!k) { Py_DECREF(d); return NULL; }
            PyObject *v = dec_value(r, depth + 1);
            if (!v) { Py_DECREF(k); Py_DECREF(d); return NULL; }
            int rc = PyDict_SetItem(d, k, v);
            Py_DECREF(k); Py_DECREF(v);
            if (rc < 0) { Py_DECREF(d); return NULL; }
        }
        return d;
    }
    case T_SET: case T_FROZENSET: {
        Py_ssize_t n;
        if (r_len(r, &n) < 0) return NULL;
        PyObject *s = tag == T_SET ? PySet_New(NULL) : PyFrozenSet_New(NULL);
        if (!s) return NULL;
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *v = dec_value(r, depth + 1);
            if (!v) { Py_DECREF(s); return NULL; }
            int rc = PySet_Add(s, v);
            Py_DECREF(v);
            if (rc < 0) { Py_DECREF(s); return NULL; }
        }
        return s;
    }
    case T_GRAIN_ID: {
        if (!g_state.configured) goto unconfigured;
        int64_t cat, tc, h64;
        if (dec_i64(r, &cat) < 0) return NULL;
        if (dec_i64(r, &tc) < 0) return NULL;
        PyObject *key = dec_value(r, depth + 1);
        if (!key) return NULL;
        PyObject *ext = dec_value(r, depth + 1);
        if (!ext) { Py_DECREF(key); return NULL; }
        if (dec_i64(r, &h64) < 0) { Py_DECREF(key); Py_DECREF(ext); return NULL; }
        if (cat < 0 || cat >= PyTuple_GET_SIZE(g_state.grain_cat_members) ||
            PyTuple_GET_ITEM(g_state.grain_cat_members, cat) == Py_None) {
            Py_DECREF(key); Py_DECREF(ext);
            PyErr_Format(PyExc_ValueError, "hotwire: bad grain category %lld",
                         (long long)cat);
            return NULL;
        }
        PyObject *inst = blank_instance(g_state.grain_id_cls);
        if (!inst) { Py_DECREF(key); Py_DECREF(ext); return NULL; }
        PyObject *catm = PyTuple_GET_ITEM(g_state.grain_cat_members, cat);
        Py_INCREF(catm);
        /* set_field steals its value, so a short-circuited chain would
         * leak the owned objects it never reached — consume them
         * explicitly on each early-failure branch */
        if (set_field(inst, g_state.s_category, catm) < 0 ||
            set_i64_field(inst, g_state.s_type_code, tc) < 0) {
            Py_DECREF(key); Py_DECREF(ext); Py_DECREF(inst);
            return NULL;
        }
        if (set_field(inst, g_state.s_key, key) < 0) {
            Py_DECREF(ext); Py_DECREF(inst);
            return NULL;
        }
        if (set_field(inst, g_state.s_key_ext, ext) < 0 ||
            set_i64_field(inst, g_state.s_hash64, h64) < 0) {
            Py_DECREF(inst);
            return NULL;
        }
        return inst;
    }
    case T_SILO_ADDR: {
        if (!g_state.configured) goto unconfigured;
        Py_ssize_t hn;
        if (r_len(r, &hn) < 0) return NULL;
        PyObject *host = PyUnicode_DecodeUTF8((const char *)r->p, hn, NULL);
        if (!host) return NULL;
        r->p += hn;
        int64_t port, gen, mesh, uh;
        if (dec_i64(r, &port) < 0 || dec_i64(r, &gen) < 0 ||
            dec_i64(r, &mesh) < 0 || dec_i64(r, &uh) < 0) {
            Py_DECREF(host);
            return NULL;
        }
        PyObject *inst = blank_instance(g_state.silo_cls);
        if (!inst) { Py_DECREF(host); return NULL; }
        if (set_field(inst, g_state.s_host, host) < 0 ||
            set_i64_field(inst, g_state.s_port, port) < 0 ||
            set_i64_field(inst, g_state.s_generation, gen) < 0 ||
            set_i64_field(inst, g_state.s_mesh_index, mesh) < 0 ||
            set_i64_field(inst, g_state.s_uh, uh) < 0) {
            Py_DECREF(inst);
            return NULL;
        }
        return inst;
    }
    case T_ACTIVATION_ID: {
        if (!g_state.configured) goto unconfigured;
        int64_t v;
        if (dec_i64(r, &v) < 0) return NULL;
        PyObject *inst = blank_instance(g_state.act_id_cls);
        if (!inst) return NULL;
        if (set_i64_field(inst, g_state.s_value, v) < 0) { Py_DECREF(inst); return NULL; }
        return inst;
    }
    case T_ACTIVATION_ADDR: {
        if (!g_state.configured) goto unconfigured;
        PyObject *silo = dec_value(r, depth + 1);
        if (!silo) return NULL;
        PyObject *grain = dec_value(r, depth + 1);
        if (!grain) { Py_DECREF(silo); return NULL; }
        PyObject *act = dec_value(r, depth + 1);
        if (!act) { Py_DECREF(silo); Py_DECREF(grain); return NULL; }
        PyObject *inst = blank_instance(g_state.act_addr_cls);
        if (!inst) { Py_DECREF(silo); Py_DECREF(grain); Py_DECREF(act); return NULL; }
        /* consume not-yet-stolen values on early failure (see T_GRAIN_ID) */
        if (set_field(inst, g_state.s_silo, silo) < 0) {
            Py_DECREF(grain); Py_DECREF(act); Py_DECREF(inst);
            return NULL;
        }
        if (set_field(inst, g_state.s_grain, grain) < 0) {
            Py_DECREF(act); Py_DECREF(inst);
            return NULL;
        }
        if (set_field(inst, g_state.s_activation, act) < 0) {
            Py_DECREF(inst);
            return NULL;
        }
        return inst;
    }
    case T_PICKLE: {
        if (!g_state.configured) goto unconfigured;
        Py_ssize_t n;
        if (r_len(r, &n) < 0) return NULL;
        PyObject *b = PyBytes_FromStringAndSize((const char *)r->p, n);
        if (!b) return NULL;
        r->p += n;
        PyObject *v = PyObject_CallOneArg(g_state.pickle_loads, b);
        Py_DECREF(b);
        return v;
    }
    default:
        PyErr_Format(PyExc_ValueError, "hotwire: unknown tag 0x%02x", tag);
        return NULL;
    unconfigured:
        PyErr_SetString(PyExc_RuntimeError, "hotwire: not configured");
        return NULL;
    }
}

/* ------------------------------------------------------------------ */
/* module functions                                                    */

static PyObject *hw_dumps(PyObject *self, PyObject *obj) {
    W w;
    if (w_init(&w, 256) < 0) return NULL;
    w.buf[w.len++] = (char)(uint8_t)HW_MAGIC;
    w.buf[w.len++] = (char)HW_VERSION;
    if (enc_value(&w, obj, 0) < 0) { w_free(&w); return NULL; }
    PyObject *out = PyBytes_FromStringAndSize(w.buf, w.len);
    w_free(&w);
    return out;
}

static PyObject *hw_loads(PyObject *self, PyObject *arg) {
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return NULL;
    R r = { (const uint8_t *)view.buf, (const uint8_t *)view.buf + view.len };
    PyObject *out = NULL;
    if (view.len < 2) {
        PyErr_SetString(PyExc_ValueError, "hotwire: buffer too short");
    } else if (r.p[0] != HW_MAGIC || r.p[1] != HW_VERSION) {
        PyErr_SetString(PyExc_ValueError, "hotwire: bad magic/version");
    } else {
        r.p += 2;
        out = dec_value(&r, 0);
        if (out && r.p != r.end) {
            Py_CLEAR(out);
            PyErr_SetString(PyExc_ValueError, "hotwire: trailing garbage");
        }
    }
    PyBuffer_Release(&view);
    return out;
}

static PyObject *hw_configure(PyObject *self, PyObject *args) {
    PyObject *grain_cls, *cat_members, *silo_cls, *act_cls, *addr_cls,
             *dumps_fn, *loads_fn;
    if (!PyArg_ParseTuple(args, "OOOOOOO", &grain_cls, &cat_members,
                          &silo_cls, &act_cls, &addr_cls, &dumps_fn, &loads_fn))
        return NULL;
    if (!PyTuple_Check(cat_members)) {
        PyErr_SetString(PyExc_TypeError, "cat_members must be a tuple");
        return NULL;
    }
    hw_state *s = &g_state;
#define KEEP(dst, src) do { Py_INCREF(src); Py_XSETREF(dst, src); } while (0)
    KEEP(s->grain_id_cls, grain_cls);
    KEEP(s->grain_cat_members, cat_members);
    KEEP(s->silo_cls, silo_cls);
    KEEP(s->act_id_cls, act_cls);
    KEEP(s->act_addr_cls, addr_cls);
    KEEP(s->pickle_dumps, dumps_fn);
    KEEP(s->pickle_loads, loads_fn);
#undef KEEP
#define INTERN(dst, name) do { \
        if (!dst) { dst = PyUnicode_InternFromString(name); \
                    if (!dst) return NULL; } } while (0)
    INTERN(s->s_category, "category");
    INTERN(s->s_type_code, "type_code");
    INTERN(s->s_key, "key");
    INTERN(s->s_key_ext, "key_ext");
    INTERN(s->s_hash64, "_hash64");
    INTERN(s->s_host, "host");
    INTERN(s->s_port, "port");
    INTERN(s->s_generation, "generation");
    INTERN(s->s_mesh_index, "mesh_index");
    INTERN(s->s_uh, "_uh");
    INTERN(s->s_value, "value");
    INTERN(s->s_silo, "silo");
    INTERN(s->s_grain, "grain");
    INTERN(s->s_activation, "activation");
#undef INTERN
    s->configured = 1;
    Py_RETURN_NONE;
}

/* Encode one already-fetched header-field value: top-level int
 * subclasses (IntEnums) are coerced to plain ints — the message-header
 * fast path; the decoder side restores them positionally.  Shared by
 * enc_attr_tuple and the template writer. */
static int enc_attr_value(W *w, PyObject *v) {
    if (PyLong_Check(v) && !PyLong_CheckExact(v) && !PyBool_Check(v)) {
        /* IntEnum header field -> wire int */
        int overflow = 0;
        long long ll = PyLong_AsLongLongAndOverflow(v, &overflow);
        if (overflow || (ll == -1 && PyErr_Occurred()))
            return -1;
        return (w_byte(w, T_INT) < 0 ||
                w_varint(w, zigzag(ll)) < 0) ? -1 : 0;
    }
    return enc_value(w, v, 1);
}

/* Shared core of pack_attrs/pack_frame: magic+version+T_TUPLE, then
 * tuple(getattr(obj, n) for n in names) + (extra,) without materializing
 * the intermediate tuple. */
static int enc_attr_tuple(W *w, PyObject *obj, PyObject *names,
                          PyObject *extra) {
    Py_ssize_t n = PyTuple_GET_SIZE(names);
    if (w->cap - w->len < 2 && w_grow(w, 2) < 0) return -1;
    w->buf[w->len++] = (char)(uint8_t)HW_MAGIC;
    w->buf[w->len++] = (char)HW_VERSION;
    if (w_byte(w, T_TUPLE) < 0 || w_varint(w, (uint64_t)(n + 1)) < 0)
        return -1;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *v = PyObject_GetAttr(obj, PyTuple_GET_ITEM(names, i));
        if (!v) return -1;
        int rc = enc_attr_value(w, v);
        Py_DECREF(v);
        if (rc < 0) return -1;
    }
    return enc_value(w, extra, 1);
}

/* pack_attrs(obj, names, extra) -> bytes */
static PyObject *hw_pack_attrs(PyObject *self, PyObject *args) {
    PyObject *obj, *names, *extra;
    if (!PyArg_ParseTuple(args, "OO!O", &obj, &PyTuple_Type, &names, &extra))
        return NULL;
    W w;
    if (w_init(&w, 256) < 0) return NULL;
    if (enc_attr_tuple(&w, obj, names, extra) < 0) { w_free(&w); return NULL; }
    PyObject *out = PyBytes_FromStringAndSize(w.buf, w.len);
    w_free(&w);
    return out;
}

/* frame segment cap, mirrored from runtime.wire.MAX_FRAME_SEGMENT */
#define HW_MAX_SEGMENT (128u * 1024u * 1024u)

/* configure_headers(names, enum_spec) -> None
 *
 * Caches the Message header-struct spec module-side: the field-name tuple
 * (interned for fast get/setattr) and the enum restore spec, so the
 * per-frame socket path (pack_frame/unpack_header) passes no spec
 * objects. */
static PyObject *hw_configure_headers(PyObject *self, PyObject *args) {
    PyObject *names, *enum_spec;
    if (!PyArg_ParseTuple(args, "O!O!", &PyTuple_Type, &names,
                          &PyTuple_Type, &enum_spec))
        return NULL;
    for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(names); i++) {
        if (!PyUnicode_Check(PyTuple_GET_ITEM(names, i))) {
            PyErr_SetString(PyExc_TypeError, "names must be strings");
            return NULL;
        }
    }
    for (Py_ssize_t e = 0; e < PyTuple_GET_SIZE(enum_spec); e++) {
        PyObject *pair = PyTuple_GET_ITEM(enum_spec, e);
        if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) != 2 ||
            !PyLong_Check(PyTuple_GET_ITEM(pair, 0)) ||
            !PyTuple_Check(PyTuple_GET_ITEM(pair, 1))) {
            PyErr_SetString(PyExc_TypeError,
                            "enum_spec: want (index, members) pairs");
            return NULL;
        }
    }
    /* intern the names in place for fast attribute access */
    PyObject *interned = PyTuple_New(PyTuple_GET_SIZE(names));
    if (!interned) return NULL;
    for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(names); i++) {
        PyObject *s = PyTuple_GET_ITEM(names, i);
        Py_INCREF(s);
        PyUnicode_InternInPlace(&s);
        PyTuple_SET_ITEM(interned, i, s);
    }
    Py_XSETREF(g_state.hdr_names, interned);
    Py_INCREF(enum_spec);
    Py_XSETREF(g_state.hdr_enum_spec, enum_spec);
    g_state.hdr_configured = 1;
    Py_RETURN_NONE;
}

/* Append one length-prefixed frame ([u32 hlen][u32 blen][headers][body])
 * at the current write position.  Shared by pack_frame (one frame per
 * call) and pack_batch (a whole send batch into one buffer) — the batch
 * output is bit-for-bit the concatenation of the per-frame outputs. */
static int frame_begin(W *w, Py_ssize_t *start, Py_buffer *body) {
    if (body->len > (Py_ssize_t)HW_MAX_SEGMENT) {
        PyErr_SetString(PyExc_ValueError, "hotwire: body exceeds frame cap");
        return -1;
    }
    *start = w->len;
    if (w->cap - w->len < 8 && w_grow(w, 8) < 0) return -1;
    memset(w->buf + *start, 0, 8);  /* length prefix backfilled at finish */
    w->len = *start + 8;
    return 0;
}

static int frame_finish(W *w, Py_ssize_t start, Py_buffer *body) {
    if (w->len - start - 8 > (Py_ssize_t)HW_MAX_SEGMENT) {
        PyErr_SetString(PyExc_ValueError,
                        "hotwire: headers exceed frame cap");
        return -1;
    }
    {
        uint32_t hlen = (uint32_t)(w->len - start - 8);
        uint32_t blen = (uint32_t)body->len;
        /* little-endian u32 pair, matching struct.Struct("<II") */
        char *p = w->buf + start;
        p[0] = (char)(hlen & 0xFF);
        p[1] = (char)((hlen >> 8) & 0xFF);
        p[2] = (char)((hlen >> 16) & 0xFF);
        p[3] = (char)((hlen >> 24) & 0xFF);
        p[4] = (char)(blen & 0xFF);
        p[5] = (char)((blen >> 8) & 0xFF);
        p[6] = (char)((blen >> 16) & 0xFF);
        p[7] = (char)((blen >> 24) & 0xFF);
    }
    return w_raw(w, (const char *)body->buf, body->len);
}

static int write_frame(W *w, PyObject *msg, PyObject *ttl, Py_buffer *body) {
    Py_ssize_t start;
    if (frame_begin(w, &start, body) < 0) return -1;
    if (enc_attr_tuple(w, msg, g_state.hdr_names, ttl) < 0)
        return -1;
    return frame_finish(w, start, body);
}

/* pack_frame(msg, ttl, body) -> bytes
 *
 * One C call for the whole wire frame: [u32 hlen][u32 blen][headers][body]
 * (the IncomingMessageBuffer length-prefixed layout).  Header payload
 * bytes are identical to pack_attrs(msg, hdr_names, ttl), so a peer that
 * only knows unpack_attrs decodes these frames unchanged — pack_frame
 * sheds the per-message Python-level struct.pack + two bytes-concats, not
 * the format. */
static PyObject *hw_pack_frame(PyObject *self, PyObject *args) {
    PyObject *msg, *ttl;
    Py_buffer body;
    if (!PyArg_ParseTuple(args, "OOy*", &msg, &ttl, &body))
        return NULL;
    if (!g_state.hdr_configured) {
        PyBuffer_Release(&body);
        PyErr_SetString(PyExc_RuntimeError,
                        "hotwire: headers not configured");
        return NULL;
    }
    W w;
    if (w_init(&w, 512) < 0) { PyBuffer_Release(&body); return NULL; }
    if (write_frame(&w, msg, ttl, &body) < 0) {
        w_free(&w);
        PyBuffer_Release(&body);
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize(w.buf, w.len);
    w_free(&w);
    PyBuffer_Release(&body);
    return out;
}

/* pack_batch(items) -> bytes
 *
 * Vectorized frame-batch encode: ``items`` is a sequence of
 * (msg, ttl, body_bytes) triples; the result is ONE contiguous buffer
 * holding every frame back to back — byte-identical to
 * b"".join(pack_frame(m, t, b) for m, t, b in items), so any peer that
 * decodes per-frame streams (or pack_attrs-era builds) reads batch sends
 * unchanged.  One C call per send batch replaces N pack_frame calls plus
 * the Python-level list + b"".join; any per-item failure fails the whole
 * call (the caller falls back to per-message encode, which scopes the
 * error to one message). */
static PyObject *hw_pack_batch(PyObject *self, PyObject *arg) {
    if (!g_state.hdr_configured) {
        PyErr_SetString(PyExc_RuntimeError,
                        "hotwire: headers not configured");
        return NULL;
    }
    PyObject *seq = PySequence_Fast(arg, "pack_batch: want a sequence of "
                                         "(msg, ttl, body) triples");
    if (!seq) return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    W w;
    if (w_init(&w, n > 0 ? 512 * n : 64) < 0) { Py_DECREF(seq); return NULL; }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(item) || PyTuple_GET_SIZE(item) != 3) {
            PyErr_SetString(PyExc_TypeError,
                            "pack_batch: items must be (msg, ttl, body)");
            goto fail;
        }
        Py_buffer body;
        if (PyObject_GetBuffer(PyTuple_GET_ITEM(item, 2), &body,
                               PyBUF_SIMPLE) < 0)
            goto fail;
        int rc = write_frame(&w, PyTuple_GET_ITEM(item, 0),
                             PyTuple_GET_ITEM(item, 1), &body);
        PyBuffer_Release(&body);
        if (rc < 0) goto fail;
    }
    {
        PyObject *out = PyBytes_FromStringAndSize(w.buf, w.len);
        w_free(&w);
        Py_DECREF(seq);
        return out;
    }
fail:
    w_free(&w);
    Py_DECREF(seq);
    return NULL;
}

/* Validate a varying-field index tuple against the configured header
 * spec: ints, strictly ascending, in [0, n_fields). Returns the count,
 * or -1 with an exception set. */
static Py_ssize_t check_var_indices(PyObject *vars) {
    Py_ssize_t n = PyTuple_GET_SIZE(g_state.hdr_names);
    Py_ssize_t k = PyTuple_GET_SIZE(vars);
    Py_ssize_t prev = -1;
    for (Py_ssize_t j = 0; j < k; j++) {
        PyObject *o = PyTuple_GET_ITEM(vars, j);
        if (!PyLong_Check(o)) {
            PyErr_SetString(PyExc_TypeError,
                            "var_indices: want a tuple of ints");
            return -1;
        }
        Py_ssize_t i = PyLong_AsSsize_t(o);
        if (i == -1 && PyErr_Occurred()) return -1;
        if (i <= prev || i >= n) {
            PyErr_SetString(PyExc_ValueError,
                            "var_indices: must be strictly ascending "
                            "and within the header field count");
            return -1;
        }
        prev = i;
    }
    return k;
}

/* make_header_template(msg, var_indices) -> tuple of bytes
 *
 * Pre-encode the INVARIANT portion of a message-header frame: the
 * returned tuple holds k+1 byte chunks — the header preamble
 * (magic/version/T_TUPLE/count) plus the encoded runs of invariant
 * fields between (and around) the k varying fields named by
 * ``var_indices``.  pack_batch_tmpl below memcpys the chunks and
 * encodes only the varying fields per message, producing bytes
 * identical to pack_frame whenever the invariant field VALUES match the
 * message the template was built from (the caller keys its template
 * cache on exactly those values). */
static PyObject *hw_make_header_template(PyObject *self, PyObject *args) {
    PyObject *msg, *vars;
    if (!PyArg_ParseTuple(args, "OO!", &msg, &PyTuple_Type, &vars))
        return NULL;
    if (!g_state.hdr_configured) {
        PyErr_SetString(PyExc_RuntimeError,
                        "hotwire: headers not configured");
        return NULL;
    }
    Py_ssize_t k = check_var_indices(vars);
    if (k < 0) return NULL;
    Py_ssize_t n = PyTuple_GET_SIZE(g_state.hdr_names);
    PyObject *chunks = PyTuple_New(k + 1);
    if (!chunks) return NULL;
    W w;
    if (w_init(&w, 256) < 0) { Py_DECREF(chunks); return NULL; }
    /* preamble: identical to enc_attr_tuple's opening bytes */
    if (w_byte(&w, HW_MAGIC) < 0 || w_byte(&w, HW_VERSION) < 0 ||
        w_byte(&w, T_TUPLE) < 0 ||
        w_varint(&w, (uint64_t)(n + 1)) < 0)
        goto fail;
    {
        Py_ssize_t vi = 0;
        for (Py_ssize_t i = 0; i < n; i++) {
            if (vi < k &&
                i == PyLong_AsSsize_t(PyTuple_GET_ITEM(vars, vi))) {
                /* varying field: close the current invariant chunk */
                PyObject *c = PyBytes_FromStringAndSize(w.buf, w.len);
                if (!c) goto fail;
                PyTuple_SET_ITEM(chunks, vi, c);
                w.len = 0;
                vi++;
                continue;
            }
            PyObject *v = PyObject_GetAttr(
                msg, PyTuple_GET_ITEM(g_state.hdr_names, i));
            if (!v) goto fail;
            int rc = enc_attr_value(&w, v);
            Py_DECREF(v);
            if (rc < 0) goto fail;
        }
        PyObject *tail = PyBytes_FromStringAndSize(w.buf, w.len);
        if (!tail) goto fail;
        PyTuple_SET_ITEM(chunks, k, tail);
    }
    w_free(&w);
    return chunks;
fail:
    w_free(&w);
    Py_DECREF(chunks);
    return NULL;
}

/* pack_batch_tmpl(chunks, var_indices, items) -> bytes
 *
 * Template-mode batch encode (the pre-encoded header-prefix cache):
 * each (msg, ttl, body) frame is written as
 *
 *   [len prefix][chunk0][enc var0][chunk1][enc var1]...[chunkK][ttl][body]
 *
 * — the invariant header runs are memcpy'd from the cached template and
 * only the varying fields (correlation id, per-message stamps, body
 * splice) are encoded per message.  Byte-identical to pack_batch /
 * N pack_frame calls when the template matches (property-tested).  Any
 * per-item failure fails the whole call; the caller falls back to the
 * per-message encode, which scopes the error to one frame. */
static PyObject *hw_pack_batch_tmpl(PyObject *self, PyObject *args) {
    PyObject *chunks, *vars, *arg;
    if (!PyArg_ParseTuple(args, "O!O!O", &PyTuple_Type, &chunks,
                          &PyTuple_Type, &vars, &arg))
        return NULL;
    if (!g_state.hdr_configured) {
        PyErr_SetString(PyExc_RuntimeError,
                        "hotwire: headers not configured");
        return NULL;
    }
    Py_ssize_t k = check_var_indices(vars);
    if (k < 0) return NULL;
    if (PyTuple_GET_SIZE(chunks) != k + 1) {
        PyErr_SetString(PyExc_ValueError,
                        "pack_batch_tmpl: want len(var_indices)+1 chunks");
        return NULL;
    }
    for (Py_ssize_t j = 0; j <= k; j++) {
        if (!PyBytes_Check(PyTuple_GET_ITEM(chunks, j))) {
            PyErr_SetString(PyExc_TypeError,
                            "pack_batch_tmpl: chunks must be bytes");
            return NULL;
        }
    }
    PyObject *seq = PySequence_Fast(arg, "pack_batch_tmpl: want a sequence "
                                         "of (msg, ttl, body) triples");
    if (!seq) return NULL;
    Py_ssize_t count = PySequence_Fast_GET_SIZE(seq);
    W w;
    if (w_init(&w, count > 0 ? 256 * count : 64) < 0) {
        Py_DECREF(seq);
        return NULL;
    }
    for (Py_ssize_t i = 0; i < count; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(item) || PyTuple_GET_SIZE(item) != 3) {
            PyErr_SetString(PyExc_TypeError,
                            "pack_batch_tmpl: items must be "
                            "(msg, ttl, body)");
            goto fail;
        }
        PyObject *msg = PyTuple_GET_ITEM(item, 0);
        Py_buffer body;
        if (PyObject_GetBuffer(PyTuple_GET_ITEM(item, 2), &body,
                               PyBUF_SIMPLE) < 0)
            goto fail;
        Py_ssize_t start;
        int rc = frame_begin(&w, &start, &body);
        for (Py_ssize_t j = 0; rc == 0 && j < k; j++) {
            PyObject *c = PyTuple_GET_ITEM(chunks, j);
            rc = w_raw(&w, PyBytes_AS_STRING(c), PyBytes_GET_SIZE(c));
            if (rc == 0) {
                PyObject *name = PyTuple_GET_ITEM(
                    g_state.hdr_names,
                    PyLong_AsSsize_t(PyTuple_GET_ITEM(vars, j)));
                PyObject *v = PyObject_GetAttr(msg, name);
                if (!v) { rc = -1; break; }
                rc = enc_attr_value(&w, v);
                Py_DECREF(v);
            }
        }
        if (rc == 0) {
            PyObject *tail = PyTuple_GET_ITEM(chunks, k);
            rc = w_raw(&w, PyBytes_AS_STRING(tail),
                       PyBytes_GET_SIZE(tail));
        }
        if (rc == 0)
            rc = enc_value(&w, PyTuple_GET_ITEM(item, 1), 1);  /* ttl */
        if (rc == 0)
            rc = frame_finish(&w, start, &body);
        PyBuffer_Release(&body);
        if (rc < 0) goto fail;
    }
    {
        PyObject *out = PyBytes_FromStringAndSize(w.buf, w.len);
        w_free(&w);
        Py_DECREF(seq);
        return out;
    }
fail:
    w_free(&w);
    Py_DECREF(seq);
    return NULL;
}

/* unpack_attrs(data, obj, names, enum_spec) -> extra
 *
 * Inverse of pack_attrs: decodes the T_TUPLE, setattrs each of the first
 * len(names) values onto obj (restoring enum fields per enum_spec, a
 * tuple of (index, members_tuple) pairs), and returns the trailing extra
 * value. */
static PyObject *unpack_attrs_span(const uint8_t *buf, Py_ssize_t len,
                                   PyObject *obj, PyObject *names,
                                   PyObject *enum_spec) {
    R r = { buf, buf + len };
    Py_ssize_t n = PyTuple_GET_SIZE(names);
    PyObject *extra = NULL;
    PyObject **vals = NULL;

    if (len < 3 || r.p[0] != HW_MAGIC || r.p[1] != HW_VERSION ||
        r.p[2] != T_TUPLE) {
        PyErr_SetString(PyExc_ValueError, "hotwire: not a packed-attrs frame");
        goto done;
    }
    r.p += 3;
    uint64_t count;
    if (r_varint(&r, &count) < 0) goto done;
    if (count != (uint64_t)(n + 1)) {
        PyErr_Format(PyExc_ValueError,
                     "hotwire: field count %llu != expected %zd",
                     (unsigned long long)count, n + 1);
        goto done;
    }
    vals = PyMem_Calloc(n, sizeof(PyObject *));
    if (!vals) { PyErr_NoMemory(); goto done; }
    for (Py_ssize_t i = 0; i < n; i++) {
        vals[i] = dec_value(&r, 1);
        if (!vals[i]) goto done;
    }
    extra = dec_value(&r, 1);
    if (!extra) goto done;
    if (r.p != r.end) {
        Py_CLEAR(extra);
        PyErr_SetString(PyExc_ValueError, "hotwire: trailing garbage");
        goto done;
    }
    /* restore enum-typed fields */
    for (Py_ssize_t e = 0; e < PyTuple_GET_SIZE(enum_spec); e++) {
        PyObject *pair = PyTuple_GET_ITEM(enum_spec, e);
        if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) != 2) {
            Py_CLEAR(extra);
            PyErr_SetString(PyExc_TypeError, "enum_spec: want (index, members)");
            goto done;
        }
        Py_ssize_t idx = PyLong_AsSsize_t(PyTuple_GET_ITEM(pair, 0));
        PyObject *members = PyTuple_GET_ITEM(pair, 1);
        if (idx < 0 || idx >= n || !PyTuple_Check(members)) {
            Py_CLEAR(extra);
            PyErr_SetString(PyExc_ValueError, "enum_spec: bad entry");
            goto done;
        }
        PyObject *v = vals[idx];
        if (PyLong_CheckExact(v)) {
            Py_ssize_t ev = PyLong_AsSsize_t(v);
            if (ev < 0 || ev >= PyTuple_GET_SIZE(members) ||
                PyTuple_GET_ITEM(members, ev) == Py_None) {
                Py_CLEAR(extra);
                PyErr_Format(PyExc_ValueError,
                             "hotwire: bad enum value %zd at field %zd", ev, idx);
                goto done;
            }
            PyObject *m = PyTuple_GET_ITEM(members, ev);
            Py_INCREF(m);
            Py_SETREF(vals[idx], m);
        } else if (v != Py_None) {
            /* enum-typed header fields are None or int on the wire; any
             * other decoded object (str, tuple, ...) from a corrupt or
             * hostile peer must be rejected, matching the Python
             * fallback's strictness */
            Py_CLEAR(extra);
            PyErr_Format(PyExc_ValueError,
                         "hotwire: non-int enum value of type %.100s at "
                         "field %zd", Py_TYPE(v)->tp_name, idx);
            goto done;
        }
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        if (PyObject_SetAttr(obj, PyTuple_GET_ITEM(names, i), vals[i]) < 0) {
            Py_CLEAR(extra);
            goto done;
        }
    }
done:
    if (vals) {
        for (Py_ssize_t i = 0; i < n; i++) Py_XDECREF(vals[i]);
        PyMem_Free(vals);
    }
    return extra;
}

static PyObject *unpack_attrs_impl(PyObject *data, PyObject *obj,
                                   PyObject *names, PyObject *enum_spec) {
    Py_buffer view;
    if (PyObject_GetBuffer(data, &view, PyBUF_SIMPLE) < 0) return NULL;
    PyObject *extra = unpack_attrs_span((const uint8_t *)view.buf, view.len,
                                        obj, names, enum_spec);
    PyBuffer_Release(&view);
    return extra;
}

static PyObject *hw_unpack_attrs(PyObject *self, PyObject *args) {
    PyObject *data, *obj, *names, *enum_spec;
    if (!PyArg_ParseTuple(args, "OOO!O!", &data, &obj, &PyTuple_Type, &names,
                          &PyTuple_Type, &enum_spec))
        return NULL;
    return unpack_attrs_impl(data, obj, names, enum_spec);
}

/* unpack_header(data, msg) -> ttl
 *
 * unpack_attrs against the cached header spec (configure_headers): the
 * per-frame decode passes only the buffer and the blank Message. */
static PyObject *hw_unpack_header(PyObject *self, PyObject *args) {
    PyObject *data, *obj;
    if (!PyArg_ParseTuple(args, "OO", &data, &obj))
        return NULL;
    if (!g_state.hdr_configured) {
        PyErr_SetString(PyExc_RuntimeError,
                        "hotwire: headers not configured");
        return NULL;
    }
    return unpack_attrs_impl(data, obj, g_state.hdr_names,
                             g_state.hdr_enum_spec);
}

/* unpack_batch(data, msg_cls) -> (consumed, entries)
 *
 * Vectorized receive-side decode: parse every COMPLETE length-prefixed
 * frame out of one contiguous receive buffer in a single C call.
 * ``consumed`` is how many bytes of ``data`` were fully parsed (the
 * caller discards that prefix and keeps the partial tail for the next
 * socket read).  Each entry is a triple:
 *
 *   (msg, ttl, body_bytes)      headers were hotwire frames and decoded
 *                               straight into a blank ``msg_cls``
 *                               instance via the cached header spec;
 *   (None, header_bytes, body_bytes)
 *                               headers were NOT native (pickle-peer
 *                               frames) or failed native decode — the
 *                               caller routes them through the ordinary
 *                               per-frame decode, which reproduces the
 *                               exact per-message error semantics.
 *
 * A header-decode failure is scoped to its frame (the length prefix
 * still delimits it); an oversized frame announcement raises — the
 * stream is hostile/misaligned and the connection must drop, exactly
 * like the per-frame path. */
/* Shared parse core of unpack_batch and sock_recv_batch: walk every
 * complete frame in [base, base+len), appending entries (see the
 * unpack_batch docstring for the entry shapes).  Returns the entry list
 * and sets *consumed_out; NULL with an exception set on a hostile
 * leading announcement or allocation failure. */
static PyObject *unpack_span_batch(const uint8_t *base, Py_ssize_t len,
                                   PyObject *msg_cls,
                                   Py_ssize_t *consumed_out) {
    Py_ssize_t pos = 0;
    PyObject *out = PyList_New(0);
    if (!out) return NULL;
    while (len - pos >= 8) {
        uint32_t hlen = (uint32_t)base[pos] | ((uint32_t)base[pos + 1] << 8) |
                        ((uint32_t)base[pos + 2] << 16) |
                        ((uint32_t)base[pos + 3] << 24);
        uint32_t blen = (uint32_t)base[pos + 4] |
                        ((uint32_t)base[pos + 5] << 8) |
                        ((uint32_t)base[pos + 6] << 16) |
                        ((uint32_t)base[pos + 7] << 24);
        if (hlen > HW_MAX_SEGMENT || blen > HW_MAX_SEGMENT) {
            /* hostile/misaligned announcement: frames already parsed out
             * of this buffer must still reach the caller (the per-frame
             * path delivered them before dropping the link), so stop
             * here when progress was made — the caller's NEXT call sees
             * the bad prefix at position 0 and raises then. */
            if (pos > 0)
                break;
            PyErr_Format(PyExc_ValueError,
                         "hotwire: oversized frame announced: %u+%u",
                         (unsigned)hlen, (unsigned)blen);
            goto fail;
        }
        Py_ssize_t total = 8 + (Py_ssize_t)hlen + (Py_ssize_t)blen;
        if (len - pos < total)
            break;  /* partial tail: next socket read completes it */
        const uint8_t *hp = base + pos + 8;
        PyObject *body = PyBytes_FromStringAndSize(
            (const char *)hp + hlen, (Py_ssize_t)blen);
        if (!body) goto fail;
        PyObject *entry = NULL;
        if (hlen >= 2 && hp[0] == HW_MAGIC && hp[1] == HW_VERSION) {
            PyObject *msg = blank_instance(msg_cls);
            if (msg) {
                PyObject *ttl = unpack_attrs_span(
                    hp, (Py_ssize_t)hlen, msg, g_state.hdr_names,
                    g_state.hdr_enum_spec);
                if (ttl) {
                    entry = PyTuple_Pack(3, msg, ttl, body);
                    Py_DECREF(ttl);
                    if (!entry) { Py_DECREF(msg); Py_DECREF(body); goto fail; }
                } else {
                    PyErr_Clear();  /* scoped to this frame: raw fallback */
                }
                Py_DECREF(msg);
            } else {
                PyErr_Clear();
            }
        }
        if (entry == NULL) {
            /* pickle-peer frame (or failed native decode): hand the raw
               segments back for the ordinary per-frame decode path */
            PyObject *hdr = PyBytes_FromStringAndSize(
                (const char *)hp, (Py_ssize_t)hlen);
            if (!hdr) { Py_DECREF(body); goto fail; }
            entry = PyTuple_Pack(3, Py_None, hdr, body);
            Py_DECREF(hdr);
            if (!entry) { Py_DECREF(body); goto fail; }
        }
        Py_DECREF(body);
        int rc = PyList_Append(out, entry);
        Py_DECREF(entry);
        if (rc < 0) goto fail;
        pos += total;
    }
    *consumed_out = pos;
    return out;
fail:
    Py_DECREF(out);
    return NULL;
}

static PyObject *hw_unpack_batch(PyObject *self, PyObject *args) {
    PyObject *data, *msg_cls;
    if (!PyArg_ParseTuple(args, "OO", &data, &msg_cls))
        return NULL;
    if (!g_state.hdr_configured) {
        PyErr_SetString(PyExc_RuntimeError,
                        "hotwire: headers not configured");
        return NULL;
    }
    if (!PyType_Check(msg_cls)) {
        PyErr_SetString(PyExc_TypeError, "unpack_batch: msg_cls not a type");
        return NULL;
    }
    Py_buffer view;
    if (PyObject_GetBuffer(data, &view, PyBUF_SIMPLE) < 0) return NULL;
    Py_ssize_t pos = 0;
    PyObject *out = unpack_span_batch((const uint8_t *)view.buf, view.len,
                                      msg_cls, &pos);
    PyBuffer_Release(&view);
    if (!out) return NULL;
    {
        PyObject *consumed = PyLong_FromSsize_t(pos);
        if (!consumed) { Py_DECREF(out); return NULL; }
        PyObject *res = PyTuple_Pack(2, consumed, out);
        Py_DECREF(consumed);
        Py_DECREF(out);
        return res;
    }
}

#ifndef MS_WINDOWS
/* sock_recv_batch(fd, tail, msg_cls, bufsize=65536)
 *     -> (entries, tail2, eof, nrecv)  |  None when not readable
 *
 * The vectored receive pump: ONE C call per socket-ready event replaces
 * the Python recv -> buffer-append -> decode_frames chain.  The previous
 * read's partial-frame ``tail`` and a fresh ``recv`` (GIL released
 * around the syscall) are parsed in a single pass through the same frame
 * walk as ``unpack_batch``; ``tail2`` is the new partial remainder and
 * ``eof`` is True on an orderly shutdown (recv() == 0).  EAGAIN returns
 * None — the caller waits for readability and calls again.  A hostile
 * leading announcement raises ValueError exactly like ``unpack_batch``
 * (frames parsed ahead of one were already returned by the PREVIOUS
 * call; the caller also screens ``tail2`` with ``leads_hostile_frame``
 * so a peer that never sends another byte still drops promptly). */
static PyObject *hw_sock_recv_batch(PyObject *self, PyObject *args) {
    int fd;
    Py_buffer tail;
    PyObject *msg_cls;
    Py_ssize_t bufsize = 1 << 16;
    if (!PyArg_ParseTuple(args, "iy*O|n", &fd, &tail, &msg_cls, &bufsize))
        return NULL;
    if (!g_state.hdr_configured || !PyType_Check(msg_cls) || bufsize <= 0) {
        PyBuffer_Release(&tail);
        PyErr_SetString(PyExc_ValueError,
                        "sock_recv_batch: headers not configured / bad args");
        return NULL;
    }
    char *buf = PyMem_Malloc(tail.len + bufsize);
    if (!buf) { PyBuffer_Release(&tail); return PyErr_NoMemory(); }
    if (tail.len)
        memcpy(buf, tail.buf, tail.len);
    Py_ssize_t tlen = tail.len;
    PyBuffer_Release(&tail);
    ssize_t n;
    Py_BEGIN_ALLOW_THREADS
    do {
        n = recv(fd, buf + tlen, (size_t)bufsize, 0);
    } while (n < 0 && errno == EINTR);
    Py_END_ALLOW_THREADS
    if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
            PyMem_Free(buf);
            Py_RETURN_NONE;
        }
        PyErr_SetFromErrno(PyExc_OSError);
        PyMem_Free(buf);
        return NULL;
    }
    {
        Py_ssize_t total = tlen + (Py_ssize_t)n;
        Py_ssize_t consumed = 0;
        PyObject *entries = unpack_span_batch((const uint8_t *)buf, total,
                                              msg_cls, &consumed);
        if (!entries) { PyMem_Free(buf); return NULL; }
        PyObject *tail2 = PyBytes_FromStringAndSize(buf + consumed,
                                                    total - consumed);
        PyMem_Free(buf);
        if (!tail2) { Py_DECREF(entries); return NULL; }
        PyObject *nrecv = PyLong_FromSsize_t((Py_ssize_t)n);
        if (!nrecv) { Py_DECREF(entries); Py_DECREF(tail2); return NULL; }
        PyObject *res = PyTuple_Pack(4, entries, tail2,
                                     n == 0 ? Py_True : Py_False, nrecv);
        Py_DECREF(entries);
        Py_DECREF(tail2);
        Py_DECREF(nrecv);
        return res;
    }
}

/* sock_writev(fd, chunks) -> bytes written
 *
 * The vectored egress half: one ``writev`` syscall (GIL released) sends
 * a whole encode_message_batch chunk list without the Python-level
 * b"".join copy.  May write a PARTIAL prefix (kernel buffer full) — the
 * caller computes the remainder and falls back to its buffered path.
 * Raises BlockingIOError when nothing could be written (EAGAIN), OSError
 * on a dead socket.  At most IOV_MAX chunks ride one call; the caller
 * loops for longer lists. */
#ifndef IOV_MAX
#define IOV_MAX 1024
#endif
static PyObject *hw_sock_writev(PyObject *self, PyObject *args) {
    int fd;
    PyObject *arg;
    if (!PyArg_ParseTuple(args, "iO", &fd, &arg))
        return NULL;
    PyObject *seq = PySequence_Fast(arg, "sock_writev: want a sequence of "
                                         "bytes chunks");
    if (!seq) return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    if (n > IOV_MAX)
        n = IOV_MAX;
    struct iovec *iov = PyMem_Malloc((n ? n : 1) * sizeof(struct iovec));
    Py_buffer *views = PyMem_Calloc(n ? n : 1, sizeof(Py_buffer));
    if (!iov || !views) {
        PyMem_Free(iov); PyMem_Free(views); Py_DECREF(seq);
        return PyErr_NoMemory();
    }
    Py_ssize_t got = 0;
    ssize_t sent = 0;
    for (; got < n; got++) {
        if (PyObject_GetBuffer(PySequence_Fast_GET_ITEM(seq, got),
                               &views[got], PyBUF_SIMPLE) < 0)
            goto fail;
        iov[got].iov_base = views[got].buf;
        iov[got].iov_len = (size_t)views[got].len;
    }
    Py_BEGIN_ALLOW_THREADS
    do {
        sent = writev(fd, iov, (int)n);
    } while (sent < 0 && errno == EINTR);
    Py_END_ALLOW_THREADS
    if (sent < 0) {
        PyErr_SetFromErrno(PyExc_OSError);  /* EAGAIN -> BlockingIOError */
        goto fail;
    }
    for (Py_ssize_t i = 0; i < got; i++)
        PyBuffer_Release(&views[i]);
    PyMem_Free(iov); PyMem_Free(views); Py_DECREF(seq);
    return PyLong_FromSsize_t((Py_ssize_t)sent);
fail:
    for (Py_ssize_t i = 0; i < got; i++)
        PyBuffer_Release(&views[i]);
    PyMem_Free(iov); PyMem_Free(views); Py_DECREF(seq);
    return NULL;
}

/* bind_reuseport(host, port) -> fd
 *
 * One listening socket in an SO_REUSEPORT accept group (the
 * multi-process silo's advertised endpoint): the option is set BEFORE
 * bind — the kernel's admission rule for joining a group — so every
 * worker process that calls this with the same (host, port) gets its
 * own kernel accept queue and the kernel hash-balances incoming
 * connections across them.  Raises OSError where the platform has no
 * SO_REUSEPORT rather than silently binding without it (a group member
 * that never joined would steal nothing, but one that joined and never
 * accepts black-holes its share — better to fail loudly). */
static PyObject *hw_bind_reuseport(PyObject *self, PyObject *args) {
    const char *host;
    int port;
    if (!PyArg_ParseTuple(args, "si", &host, &port))
        return NULL;
#ifndef SO_REUSEPORT
    PyErr_SetString(PyExc_OSError, "SO_REUSEPORT not supported here");
    return NULL;
#else
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0)
        return PyErr_SetFromErrno(PyExc_OSError);
    int one = 1;
    struct sockaddr_in sa;
    memset(&sa, 0, sizeof(sa));
    sa.sin_family = AF_INET;
    sa.sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, host, &sa.sin_addr) != 1) {
        close(fd);
        PyErr_Format(PyExc_ValueError, "bind_reuseport: bad host %s", host);
        return NULL;
    }
    if (setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one)) < 0 ||
        setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one)) < 0 ||
        bind(fd, (struct sockaddr *)&sa, sizeof(sa)) < 0 ||
        listen(fd, 128) < 0) {
        PyErr_SetFromErrno(PyExc_OSError);
        close(fd);
        return NULL;
    }
    return PyLong_FromLong(fd);
#endif
}

/* SPSC shm ring primitives — the cross-process staging ring's hot half.
 *
 * Layout (shared with the pure-Python twin in runtime/multiproc.py —
 * a native producer and a Python consumer interoperate):
 *   [0:8]   write_cum     producer-only writer
 *   [8:16]  pushed_msgs   producer-only writer
 *   [64:72] read_cum      consumer-only writer (own cache line)
 *   [72:80] drained_msgs  consumer-only writer
 *   [128:]  data (capacity bytes, 8-aligned); records are
 *           u32 len | u32 n_msgs | payload, padded to 8; u32
 *           0xFFFFFFFF marks an end-of-region wrap skip.
 * Each counter has exactly one writer, so plain stores suffice for the
 * owner side; the cross-side loads/stores pair acquire/release so the
 * payload bytes are visible before the counter that publishes them. */
#define SHM_HDR 128
#define SHM_WRAP 0xFFFFFFFFu

/* shm_push(buf, capacity, payload, n_msgs) -> bool (False = ring full) */
static PyObject *hw_shm_push(PyObject *self, PyObject *args) {
    Py_buffer buf, payload;
    Py_ssize_t cap;
    unsigned long long n_msgs;
    if (!PyArg_ParseTuple(args, "w*ny*K", &buf, &cap, &payload, &n_msgs))
        return NULL;
    if (cap <= 64 || (cap & 7) || buf.len < SHM_HDR + cap) {
        PyBuffer_Release(&buf); PyBuffer_Release(&payload);
        PyErr_SetString(PyExc_ValueError, "shm_push: bad ring buffer");
        return NULL;
    }
    uint8_t *base = (uint8_t *)buf.buf;
    uint8_t *data = base + SHM_HDR;
    uint64_t ln = (uint64_t)payload.len;
    uint64_t rec = 8 + ((ln + 7) & ~7ULL);
    if (rec > (uint64_t)cap - 8) {
        PyBuffer_Release(&buf); PyBuffer_Release(&payload);
        PyErr_Format(PyExc_ValueError,
                     "shm_push: record of %llu bytes exceeds capacity %zd",
                     (unsigned long long)ln, cap);
        return NULL;
    }
    uint64_t wc = __atomic_load_n((uint64_t *)(base + 0), __ATOMIC_RELAXED);
    uint64_t rc = __atomic_load_n((uint64_t *)(base + 64), __ATOMIC_ACQUIRE);
    uint64_t pos = wc % (uint64_t)cap;
    uint64_t contig = (uint64_t)cap - pos;
    uint64_t need = rec + (contig < rec ? contig : 0);
    if ((uint64_t)cap - (wc - rc) < need) {
        PyBuffer_Release(&buf); PyBuffer_Release(&payload);
        Py_RETURN_FALSE;
    }
    if (contig < rec) {
        uint32_t w = SHM_WRAP;
        memcpy(data + pos, &w, 4);
        wc += contig;
        pos = 0;
    }
    uint32_t l32 = (uint32_t)ln, m32 = (uint32_t)n_msgs;
    memcpy(data + pos, &l32, 4);
    memcpy(data + pos + 4, &m32, 4);
    if (ln)
        memcpy(data + pos + 8, payload.buf, ln);
    uint64_t pushed = *(uint64_t *)(base + 8);
    __atomic_store_n((uint64_t *)(base + 0), wc + rec, __ATOMIC_RELEASE);
    __atomic_store_n((uint64_t *)(base + 8), pushed + n_msgs,
                     __ATOMIC_RELEASE);
    PyBuffer_Release(&buf); PyBuffer_Release(&payload);
    Py_RETURN_TRUE;
}

/* shm_pop(buf, capacity) -> (payload, n_msgs) | None */
static PyObject *hw_shm_pop(PyObject *self, PyObject *args) {
    Py_buffer buf;
    Py_ssize_t cap;
    if (!PyArg_ParseTuple(args, "w*n", &buf, &cap))
        return NULL;
    if (cap <= 64 || (cap & 7) || buf.len < SHM_HDR + cap) {
        PyBuffer_Release(&buf);
        PyErr_SetString(PyExc_ValueError, "shm_pop: bad ring buffer");
        return NULL;
    }
    uint8_t *base = (uint8_t *)buf.buf;
    uint8_t *data = base + SHM_HDR;
    for (;;) {
        uint64_t rc = __atomic_load_n((uint64_t *)(base + 64),
                                      __ATOMIC_RELAXED);
        uint64_t wc = __atomic_load_n((uint64_t *)(base + 0),
                                      __ATOMIC_ACQUIRE);
        if (wc == rc) {
            PyBuffer_Release(&buf);
            Py_RETURN_NONE;
        }
        uint64_t pos = rc % (uint64_t)cap;
        uint32_t l32, m32;
        memcpy(&l32, data + pos, 4);
        if (l32 == SHM_WRAP) {
            __atomic_store_n((uint64_t *)(base + 64),
                             rc + ((uint64_t)cap - pos), __ATOMIC_RELEASE);
            continue;
        }
        memcpy(&m32, data + pos + 4, 4);
        uint64_t rec = 8 + (((uint64_t)l32 + 7) & ~7ULL);
        if (rec > (uint64_t)cap - pos) {
            PyBuffer_Release(&buf);
            PyErr_SetString(PyExc_ValueError, "shm_pop: corrupt record");
            return NULL;
        }
        PyObject *payload = PyBytes_FromStringAndSize(
            (const char *)(data + pos + 8), (Py_ssize_t)l32);
        if (!payload) { PyBuffer_Release(&buf); return NULL; }
        uint64_t drained = *(uint64_t *)(base + 72);
        __atomic_store_n((uint64_t *)(base + 64), rc + rec,
                         __ATOMIC_RELEASE);
        __atomic_store_n((uint64_t *)(base + 72), drained + m32,
                         __ATOMIC_RELEASE);
        PyObject *res = Py_BuildValue("(Nk)", payload,
                                      (unsigned long)m32);
        PyBuffer_Release(&buf);
        return res;
    }
}
#endif /* !MS_WINDOWS */

static PyMethodDef hw_methods[] = {
    {"dumps", hw_dumps, METH_O,
     "Encode a value to hotwire bytes (magic-prefixed)."},
    {"loads", hw_loads, METH_O,
     "Decode hotwire bytes back to a value."},
    {"pack_attrs", hw_pack_attrs, METH_VARARGS,
     "pack_attrs(obj, names, extra) -> bytes: encode getattr'd fields."},
    {"unpack_attrs", hw_unpack_attrs, METH_VARARGS,
     "unpack_attrs(data, obj, names, enum_spec) -> extra: decode + setattr."},
    {"configure_headers", hw_configure_headers, METH_VARARGS,
     "configure_headers(names, enum_spec): cache the Message header spec."},
    {"pack_frame", hw_pack_frame, METH_VARARGS,
     "pack_frame(msg, ttl, body) -> bytes: full length-prefixed frame."},
    {"pack_batch", hw_pack_batch, METH_O,
     "pack_batch(items) -> bytes: encode (msg, ttl, body) triples into "
     "one contiguous frame-batch buffer."},
    {"make_header_template", hw_make_header_template, METH_VARARGS,
     "make_header_template(msg, var_indices) -> chunk tuple: pre-encode "
     "the invariant header runs around the varying fields."},
    {"pack_batch_tmpl", hw_pack_batch_tmpl, METH_VARARGS,
     "pack_batch_tmpl(chunks, var_indices, items) -> bytes: template-"
     "mode frame-batch encode (memcpy invariant runs, encode varying "
     "fields per message)."},
    {"unpack_header", hw_unpack_header, METH_VARARGS,
     "unpack_header(data, msg) -> ttl: decode + setattr via cached spec."},
    {"unpack_batch", hw_unpack_batch, METH_VARARGS,
     "unpack_batch(data, msg_cls) -> (consumed, entries): decode every "
     "complete frame out of one receive buffer."},
#ifndef MS_WINDOWS
    {"sock_recv_batch", hw_sock_recv_batch, METH_VARARGS,
     "sock_recv_batch(fd, tail, msg_cls, bufsize=65536) -> "
     "(entries, tail2, eof, nrecv) | None: one recv + frame-batch "
     "decode per socket-ready event."},
    {"sock_writev", hw_sock_writev, METH_VARARGS,
     "sock_writev(fd, chunks) -> bytes written: vectored send of an "
     "encoded chunk list (partial writes possible)."},
    {"bind_reuseport", hw_bind_reuseport, METH_VARARGS,
     "bind_reuseport(host, port) -> fd: listening socket in an "
     "SO_REUSEPORT accept group (option set before bind)."},
    {"shm_push", hw_shm_push, METH_VARARGS,
     "shm_push(buf, capacity, payload, n_msgs) -> bool: append one "
     "record to a cross-process SPSC shm ring (False = full)."},
    {"shm_pop", hw_shm_pop, METH_VARARGS,
     "shm_pop(buf, capacity) -> (payload, n_msgs) | None: pop one "
     "record from a cross-process SPSC shm ring."},
#endif
    {"configure", hw_configure, METH_VARARGS,
     "configure(GrainId, cat_members, SiloAddress, ActivationId, "
     "ActivationAddress, pickle_dumps, restricted_loads)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef hw_module = {
    PyModuleDef_HEAD_INIT, "_hotwire",
    "Native wire-tier codec for orleans_tpu.", -1, hw_methods,
};

PyMODINIT_FUNC PyInit__hotwire(void) {
    memset(&g_state, 0, sizeof(g_state));
    empty_args = PyTuple_New(0);
    if (!empty_args) return NULL;
    return PyModule_Create(&hw_module);
}
