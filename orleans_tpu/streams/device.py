"""Device-tier stream provider: namespace fan-out compiled onto the bulk
collectives.

The per-subscriber delivery loop of the host-tier providers (one envelope
per ``SubscriptionHandle`` — pubsub.deliver_to_consumer) is replaced, for
vector-grain consumers, by the PR-13 broadcast machinery: the subscriber
table of a namespace is materialized as ONE dense edge list per
(vector-class, method) group, and publishing a batch compiles into
``stream_fanout`` edge exchanges — one ``parallel.transport`` hop per silo
per delivery batch instead of one envelope per subscriber (the DrJAX
broadcast-as-primitive direction, arXiv 2403.07128).

Sequence tokens and rewind ride the existing :class:`PooledQueueCache`:
every produced item consumes one token (item-cumulative, like the
persistent provider's ``QueueBatch.seq``), each delivery group owns a
cache cursor, and a rewound subscription replays exactly-from-token
through a solo catch-up cursor that merges into the fused edge list once
it reaches the group's position. Backpressure is the cache's
``under_pressure`` signal surfaced through the silo's queue-wait-trend —
no new mechanism.

QoS invariant (regression-guarded since the batched-ingress PRs): stream
delivery batches ride APPLICATION envelopes end to end — PING/SYSTEM
lanes never carry them.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any

import numpy as np

from ..core.errors import StreamError
from ..core.ids import GrainCategory, GrainId, GrainType
from .cache import PooledQueueCache
from .core import (StreamId, StreamProvider, StreamSignal,
                   SubscriptionHandle)
from .persistent import QueueBatch

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.streams.device")

__all__ = ["DeviceSubscription", "DeviceStreamProvider",
           "add_device_streams"]

# keys hashed per event-loop slice during an ownership-partition rebuild:
# a 1M-subscriber edge list is ~250 slices with a loop yield between
# each, so membership probes keep answering while the table rebuilds
# (hashing the whole list inline would stall the loop for seconds and
# fabricate suspicion votes — the QoS failure the gauntlet scenario
# guards). The slice is sized so ONE slice's hashing stays well under
# the membership probe period: a probe that lands mid-rebuild waits at
# most one slice, not the whole pass.
_HASH_SLICE = 4096
# edge-events per stacked dispatch round: items of one cached batch stack
# item-major (np.tile targets + np.repeat payload rows) up to this bound,
# so a celebrity-sized edge list still dispatches in bounded host memory
_STACK_LIMIT = 1 << 20


def _owner_hash(type_code: int, key: int) -> int:
    """The ring-routing hash of a dense int key WITHOUT touching the
    GrainId intern table (partitioning a million-key edge list through
    ``for_grain`` would churn the bounded intern cache that per-key
    traffic relies on). Constructing the frozen dataclass directly
    computes the same ``uniform_hash`` as ``GrainId.for_grain``."""
    return GrainId(GrainCategory.GRAIN, type_code, int(key)).uniform_hash


@dataclass
class DeviceSubscription:
    """One vector-grain subscription: every event published to
    ``namespace`` is delivered to rows ``keys`` of ``vcls`` through
    ``method``. Until a rewound subscription (``from_token``) catches up
    it replays through a solo cursor; ``live`` flips when it merges into
    the group's fused edge list."""

    namespace: str
    vcls: type
    method: str
    keys: np.ndarray
    sub_id: int
    from_token: int | None = None
    live: bool = False
    # ownership-partition cache (ring-fingerprint keyed) for the solo
    # catch-up phase; the live phase uses the group's
    parts: dict | None = None
    ring_sig: tuple | None = None


class _FanoutGroup:
    """The anchor-side subscriber table for one (namespace, vector-class,
    method): live subscriptions fused into ONE dense edge list (rebuilt on
    subscribe/unsubscribe at batch boundaries), one cache cursor, and the
    ownership partition cached per ring fingerprint."""

    def __init__(self, ns_name: str, vcls: type, method: str,
                 cache: PooledQueueCache):
        self.vcls = vcls
        self.method = method
        self.subs: dict[int, DeviceSubscription] = {}
        self.edges = np.zeros(0, dtype=np.int64)
        self.parts: dict | None = None
        self.ring_sig: tuple | None = None
        # group cursor starts at the write head: a new group only hears
        # batches produced after it exists (pre-subscribe backlog belongs
        # to rewound subscriptions' catch-up cursors)
        self.cursor = cache.new_cursor(("grp", ns_name, vcls.__name__,
                                        method), from_oldest=False)
        # serializes deliveries with subscribe/unsubscribe drains so an
        # edge-list rebuild never lands mid-batch (changes take effect at
        # batch boundaries — the per-consumer order contract)
        self.lock = asyncio.Lock()

    def rebuild(self) -> None:
        arrs = [s.keys for s in self.subs.values() if s.live]
        self.edges = (np.concatenate(arrs) if arrs
                      else np.zeros(0, dtype=np.int64))
        self.parts = None
        self.ring_sig = None


class _Namespace:
    """Per-namespace pump state: one PooledQueueCache, item-cumulative
    sequence tokens, the fan-out groups, and rewound catch-up cursors."""

    def __init__(self, name: str, capacity: int):
        self.name = name
        self.cache = PooledQueueCache(capacity=capacity)
        self.seq = 0                       # next item sequence token
        self.groups: dict[tuple, _FanoutGroup] = {}
        # sub_id -> (subscription, solo cursor) while catching up
        self.catchup: dict[int, tuple] = {}
        self.wake = asyncio.Event()
        self.publish_ts: dict[int, float] = {}   # cache token -> loop.time
        self.task: asyncio.Task | None = None


class DeviceStreamProvider(StreamProvider):
    """Stream provider whose consumers are vector-grain rows and whose
    delivery path is the bulk-collective fan-out (``engine.stream_fanout``
    → broadcast edge exchanges under the tick fence). Subscribe whole key
    sets with :meth:`subscribe_keys`; ``StreamRef.subscribe`` bridges
    single-key vector consumers onto the same table."""

    def __init__(self, silo: "Silo", name: str,
                 cache_capacity: int | None = None,
                 chunk: int = 16384,
                 backpressure_poll: float = 0.005):
        super().__init__(silo, name)
        self.cache_capacity = int(
            cache_capacity
            if cache_capacity is not None
            else getattr(silo.config, "stream_device_cache_capacity", 1024))
        self.chunk = chunk
        self.backpressure_poll = backpressure_poll
        self._namespaces: dict[str, _Namespace] = {}
        self._sub_seq = 0
        self._handle_subs: dict[str, DeviceSubscription] = {}
        self._running = False
        # last stacked delivery-group size (edge-events per dispatch) —
        # the streams.delivery_group gauge source
        self.last_delivery_group = 0

    # -- lifecycle -------------------------------------------------------
    async def start(self) -> None:
        self._running = True
        for ns in self._namespaces.values():
            self._ensure_pump(ns)

    async def stop(self) -> None:
        self._running = False
        tasks = []
        for ns in self._namespaces.values():
            ns.wake.set()
            if ns.task is not None:
                ns.task.cancel()
                tasks.append(ns.task)
                ns.task = None
        for t in tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass

    def _ns(self, name: str) -> _Namespace:
        ns = self._namespaces.get(name)
        if ns is None:
            ns = _Namespace(name, self.cache_capacity)
            self._namespaces[name] = ns
            if self._running:
                self._ensure_pump(ns)
        return ns

    def _ensure_pump(self, ns: _Namespace) -> None:
        if ns.task is None:
            ns.task = asyncio.get_running_loop().create_task(
                self._pump(ns))

    # -- subscribe surface ----------------------------------------------
    def _vector_class(self, vcls_or_name) -> type:
        name = (vcls_or_name if isinstance(vcls_or_name, str)
                else vcls_or_name.__name__)
        vcls = self.silo.vector_interfaces.get(name)
        if vcls is None or self.silo.vector is None:
            raise StreamError(
                f"DeviceStreamProvider consumers must be registered "
                f"vector-grain classes; {name!r} is not one on this silo "
                f"(host-tier consumers belong on an SMS/persistent "
                f"provider)")
        return vcls

    async def subscribe_keys(self, namespace: str, vcls: type, keys,
                             method: str = "on_next",
                             from_token: int | None = None
                             ) -> DeviceSubscription:
        """Subscribe dense-regime rows ``keys`` of ``vcls`` to every event
        of ``namespace``. Takes effect at a batch boundary: the group
        drains in-flight batches against the OLD edge list first, so no
        subscriber sees a partial batch. ``from_token`` rewinds: the new
        subscription replays exactly-from-token out of the cache window
        (clamped to oldest-cached, the reference's replay contract)
        through the same bulk path, then merges into the fused list."""
        vcls = self._vector_class(vcls)
        rt = self.silo.vector
        tbl = rt.table(vcls)
        keys = np.asarray(keys, dtype=np.int64).reshape(-1)
        if keys.size and (keys.min() < 0 or keys.max() >= tbl.dense_n):
            raise StreamError(
                f"device stream subscribers must be dense-regime keys in "
                f"[0, {tbl.dense_n}); hashed-key subscriber sets are a "
                f"ROADMAP follow-on")
        rt.method_of(vcls, method)  # typo fails at subscribe, not publish
        ns = self._ns(namespace)
        grp = ns.groups.get((vcls.__name__, method))
        if grp is None:
            grp = _FanoutGroup(namespace, vcls, method, ns.cache)
            ns.groups[(vcls.__name__, method)] = grp
        self._sub_seq += 1
        sub = DeviceSubscription(namespace, vcls, method, keys,
                                 self._sub_seq, from_token)
        grp.subs[sub.sub_id] = sub
        if from_token is None:
            async with grp.lock:
                await self._drain_group(ns, grp)
                sub.live = True
                grp.rebuild()
        else:
            cur = ns.cache.new_cursor(("sub", sub.sub_id),
                                      from_oldest=True)
            ns.catchup[sub.sub_id] = (sub, cur)
            ns.wake.set()
        self.silo.stats.increment("streams.device.subscribed", keys.size)
        return sub

    async def unsubscribe_keys(self, sub: DeviceSubscription) -> None:
        """Remove a subscription at the next batch boundary: batches the
        group already holds cursors past still deliver; nothing after the
        rebuild does."""
        ns = self._namespaces.get(sub.namespace)
        if ns is None:
            return
        grp = ns.groups.get((sub.vcls.__name__, sub.method))
        entry = ns.catchup.pop(sub.sub_id, None)
        if entry is not None:
            ns.cache.remove_cursor(("sub", sub.sub_id))
        if grp is not None and sub.sub_id in grp.subs:
            async with grp.lock:
                await self._drain_group(ns, grp)
                del grp.subs[sub.sub_id]
                grp.rebuild()
                if not grp.subs:
                    ns.cache.remove_cursor(grp.cursor.consumer_key)
                    del ns.groups[(sub.vcls.__name__, sub.method)]

    # StreamRef.subscribe bridge: a single-key vector consumer is a
    # one-row subscribe_keys (the stream KEY is the row key)
    async def register_consumer(self, handle: SubscriptionHandle) -> None:
        vcls = self._vector_class(handle.interface_name)
        key = handle.grain_id.key
        sub = await self.subscribe_keys(
            handle.stream.namespace, vcls, [int(key)],
            method=handle.method_name, from_token=handle.from_token)
        self._handle_subs[handle.handle_id] = sub

    async def unregister_consumer(self, handle: SubscriptionHandle) -> None:
        sub = self._handle_subs.pop(handle.handle_id, None)
        if sub is not None:
            await self.unsubscribe_keys(sub)

    async def consumer_handles(self, stream: StreamId
                               ) -> list[SubscriptionHandle]:
        # key-set subscriptions are not per-handle records, so the
        # handle-form enumeration is empty by design; introspect via
        # the groups' DeviceSubscription objects instead
        return []

    # -- producer surface ------------------------------------------------
    async def produce(self, stream: StreamId, items: list) -> int:
        """Append a batch, assign item-cumulative sequence tokens, wake
        the pump. Returns the first token. Blocks (cooperatively) while
        the cache is under pressure — the wait is surfaced through the
        silo's queue-wait-trend shed signal, not a new mechanism."""
        ns = self._ns(stream.namespace)
        st = self.silo.stats
        data = []
        for it in items:
            if isinstance(it, StreamSignal):
                # device-tier kernel methods cannot take the signal call
                # shape (the implicit_consumers host-only rule); counted
                # and dropped rather than poisoning a batch
                st.increment("streams.device.signals_dropped")
                continue
            if not isinstance(it, dict):
                raise StreamError(
                    "device stream items must be dicts of method args "
                    f"(field -> value); got {type(it).__name__}")
            data.append(it)
        loop = asyncio.get_running_loop()
        if ns.cache.under_pressure:
            t0 = loop.time()
            st.increment("streams.device.backpressure_waits")
            while ns.cache.under_pressure and self._running:
                ns.wake.set()
                await asyncio.sleep(self.backpressure_poll)
            waited = loop.time() - t0
            st.observe("streams.produce.wait.seconds", waited)
            trend = getattr(self.silo, "shed_trend", None)
            if trend is not None:
                trend.note(waited)
        first = ns.seq
        ns.seq += len(data)
        cb = ns.cache.add(QueueBatch(stream=stream, items=data, seq=first))
        ns.cache.resolved_streams.add(stream)
        ns.publish_ts[cb.token] = loop.time()
        st.increment("streams.device.produced", len(data))
        ns.wake.set()
        return first

    # -- pump ------------------------------------------------------------
    async def _pump(self, ns: _Namespace) -> None:
        while self._running:
            await ns.wake.wait()
            ns.wake.clear()
            try:
                await self._drain(ns)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — isolate; next wake retries
                self.silo.stats.increment("streams.device.delivery_errors")
                log.exception("device stream pump for %r failed", ns.name)
                await asyncio.sleep(0.05)

    async def _drain(self, ns: _Namespace) -> None:
        progressed = True
        while progressed:
            progressed = False
            for grp in list(ns.groups.values()):
                async with grp.lock:
                    if await self._drain_group(ns, grp):
                        progressed = True
            for sub_id, (sub, cur) in list(ns.catchup.items()):
                if await self._drain_catchup(ns, sub, cur):
                    progressed = True
            self._promote_ready(ns)
        if ns.cache.purge():
            # evicted tokens are gone from every cursor's view; drop
            # their publish stamps (tokens are contiguous, so everything
            # below the current floor is evicted)
            floor = ns.cache.write_token - ns.cache.count
            for tok in [t for t in ns.publish_ts if t < floor]:
                ns.publish_ts.pop(tok, None)

    async def _drain_group(self, ns: _Namespace, grp: _FanoutGroup) -> int:
        """Deliver every cached batch the group cursor has not passed.
        Caller holds ``grp.lock``."""
        n = 0
        while True:
            cb = ns.cache.next(grp.cursor)
            if cb is None:
                return n
            delivered = await self._deliver_batch(ns, grp, grp, cb,
                                                  cb.batch.items,
                                                  grp.edges)
            n += 1
            if delivered:
                ts = ns.publish_ts.get(cb.token)
                if ts is not None:
                    self.silo.stats.observe(
                        "streams.delivery.seconds",
                        asyncio.get_running_loop().time() - ts)

    async def _drain_catchup(self, ns: _Namespace, sub: DeviceSubscription,
                             cur) -> int:
        """Replay cached batches >= the subscription's token through the
        SAME bulk path, trimming the partial batch at the token edge
        (the deliver_to_consumer rewind contract)."""
        n = 0
        while True:
            cb = ns.cache.next(cur)
            if cb is None:
                return n
            items = list(cb.batch.items)
            base = cb.batch.seq
            ft = sub.from_token or 0
            if base + len(items) <= ft:
                n += 1
                continue
            if base < ft:
                items = items[ft - base:]
            await self._deliver_batch(ns, sub, sub, cb, items, sub.keys)
            n += 1

    def _promote_ready(self, ns: _Namespace) -> None:
        """Merge caught-up rewound subscriptions into their group's fused
        edge list: both cursors at the write head means the solo replay
        and the group view agree on what has been delivered, so the merge
        is exactly at a batch boundary."""
        head = ns.cache.write_token
        for sub_id, (sub, cur) in list(ns.catchup.items()):
            grp = ns.groups.get((sub.vcls.__name__, sub.method))
            if grp is None:
                continue
            if cur.next_token >= head and grp.cursor.next_token >= head:
                del ns.catchup[sub_id]
                ns.cache.remove_cursor(("sub", sub_id))
                sub.live = True
                grp.rebuild()

    # -- delivery --------------------------------------------------------
    async def _deliver_batch(self, ns: _Namespace, grp, holder, cb,
                             items: list, edges: np.ndarray) -> int:
        """Fan one cached batch out to ``edges``: items stack item-major
        (np.tile targets / np.repeat payload rows) so apply_received's
        first-occurrence-wins dedup rounds deliver each key's events in
        token order, partitioned by ring ownership — the local part runs
        ``stream_fanout`` directly, each peer part rides ONE
        ``__stream_deliver__`` APPLICATION envelope."""
        if not items or edges.size == 0:
            return 0
        fields = set(items[0])
        for it in items:
            if set(it) != fields:
                raise StreamError(
                    f"device stream batch items must share one arg set; "
                    f"got {sorted(fields)} vs {sorted(set(it))}")
        parts = await self._parts_for(grp.vcls, holder, edges)
        me = self.silo.silo_address
        rt = self.silo.vector
        delivered = 0
        E = int(edges.size)
        blk = max(1, _STACK_LIMIT // max(E, 1))
        for off in range(0, len(items), blk):
            chunk_items = items[off:off + blk]
            self.last_delivery_group = E * len(chunk_items)
            work = []
            for addr, pe in parts.items():
                if pe.size == 0:
                    continue
                targets, args = _stack_items(pe, chunk_items)
                if addr == me:
                    work.append(rt.stream_fanout(
                        grp.vcls, grp.method, targets, args,
                        chunk=self.chunk))
                else:
                    work.append(self._send_remote(grp, targets, args,
                                                  addr))
            for got in await asyncio.gather(*work):
                delivered += int(got)
        self.silo.stats.increment("streams.device.delivered", delivered)
        led = self.silo.ledger
        if led is not None:
            # cost attribution: the pump runs on the silo loop, charge
            # the namespace's delivery count directly
            led.charge_stream(self.name, delivered)
        return delivered

    def _send_remote(self, grp: _FanoutGroup, targets: np.ndarray,
                     args: dict, addr):
        """One peer silo's slice of a delivery batch: a single
        ``__stream_deliver__`` envelope (APPLICATION category — the QoS
        rule) carrying a pre-partitioned ``local=True`` spec; the peer's
        dispatcher runs its stream_fanout."""
        spec = {"method": grp.method, "targets": targets, "args": args,
                "chunk": self.chunk, "local": True}
        gid = GrainId.for_grain(GrainType.of(grp.vcls.__name__),
                                f"__stream__{self.name}")
        return self.silo.runtime_client.send_request(
            target_grain=gid, grain_class=grp.vcls,
            interface_name=grp.vcls.__name__,
            method_name="__stream_deliver__", args=(),
            kwargs={"spec": spec}, target_silo=addr)

    # -- ownership partition --------------------------------------------
    async def _parts_for(self, vcls: type, holder, edges: np.ndarray
                         ) -> dict:
        """The edge list split by ring owner, cached per ring fingerprint
        on the holder (group or catch-up subscription) — partitions are
        rebuilt on subscribe/unsubscribe and on membership change, never
        per delivery. Locations therefore re-resolve per round: a reshard
        or migration between rounds invalidates the fingerprint and the
        next delivery re-partitions before touching the wire."""
        ring = self.silo.locator.ring
        sig = tuple(ring.silos)
        if holder.parts is None or holder.ring_sig != sig:
            holder.parts = await self._partition(vcls, edges, ring)
            holder.ring_sig = sig
        return holder.parts

    async def _partition(self, vcls: type, edges: np.ndarray, ring
                         ) -> dict:
        me = self.silo.silo_address
        if len(ring.silos) <= 1 or edges.size == 0:
            return {me: edges}
        tc = GrainType.of(vcls.__name__).type_code
        silos = list(ring.silos)
        idx_of = {s: i for i, s in enumerate(silos)}
        uniq, inv = np.unique(edges, return_inverse=True)
        uidx = np.empty(uniq.size, dtype=np.int64)
        for s in range(0, uniq.size, _HASH_SLICE):
            e = min(s + _HASH_SLICE, uniq.size)
            for j in range(s, e):
                owner = ring.owner(_owner_hash(tc, uniq[j])) or me
                uidx[j] = idx_of.get(owner, idx_of[me])
            # keep the loop breathing mid-rebuild: PING probes and turn
            # traffic must not queue behind a million blake2b calls
            await asyncio.sleep(0)
        per_edge = uidx[inv]
        out = {}
        for i, addr in enumerate(silos):
            m = per_edge == i
            if m.any():
                out[addr] = edges[m]
        return out

    # -- observability probes (MetricsSampler streams.* sources) ---------
    def stream_backlog(self) -> float:
        """Cached batches not yet passed by every cursor."""
        return float(sum(ns.cache.count
                         for ns in self._namespaces.values()))

    def stream_cursor_lag(self) -> float:
        """Worst cursor lag (batches) behind the write head."""
        lag = 0
        for ns in self._namespaces.values():
            head = ns.cache.write_token
            for cur in ns.cache.cursors.values():
                lag = max(lag, head - cur.next_token)
        return float(lag)

    def stream_delivery_group(self) -> float:
        """Edge-events in the last stacked dispatch (sustained 1 means
        the fan-out degenerated to per-event delivery)."""
        return float(self.last_delivery_group)


def _stack_items(edges: np.ndarray, items: list) -> tuple:
    """Item-major stacking of one delivery block: targets are
    ``np.tile(edges, B)`` and every payload field repeats per edge —
    lane order == token order per key, which is exactly the order
    apply_received's dedup rounds deliver duplicates in."""
    B = len(items)
    targets = np.tile(edges, B)
    args = {}
    for f in items[0]:
        vals = np.asarray([it[f] for it in items])
        args[f] = np.repeat(vals, edges.size, axis=0)
    return targets, args


def add_device_streams(builder, name: str = "device", **kw):
    """Install a :class:`DeviceStreamProvider` (the install idiom of
    ``add_persistent_streams``): provider registered under ``name``,
    lifecycle hooked at RUNTIME_GRAIN_SERVICES."""

    def install(silo):
        provider = DeviceStreamProvider(silo, name, **kw)
        silo.stream_providers[name] = provider
        from ..runtime.silo import ServiceLifecycleStage
        silo.subscribe_lifecycle(ServiceLifecycleStage.RUNTIME_GRAIN_SERVICES,
                                 provider.start, provider.stop)

    return builder.configure(install)
