"""Virtual streams (reference L11, src/Orleans.Core/Streams/ +
src/Orleans.Runtime/Streams/): SMS direct fan-out + persistent queue-backed
providers over grain-call delivery."""

from .balancer import (
    BestFitBalancer,
    DeploymentBasedBalancer,
    LeaseBasedBalancer,
    MemoryLeaseProvider,
    QueueBalancer,
)
from .cache import PooledQueueCache, QueueCacheCursor
from .durable import (
    DurableQueueAdapter,
    FileQueueAdapter,
    SqliteQueueAdapter,
)
from .core import (StreamId, StreamProvider, StreamRef, StreamSignal,
                   SubscriptionHandle, batch_consumer)
from .device import (
    DeviceStreamProvider,
    DeviceSubscription,
    add_device_streams,
)
from .persistent import (
    GeneratorQueueAdapter,
    MemoryQueueAdapter,
    PersistentStreamProvider,
    QueueAdapter,
    QueueBatch,
    QueueReceiver,
    add_persistent_streams,
)
from .pubsub import PubSubRendezvousGrain, implicit_stream_subscription
from .sms import SMSStreamProvider, add_sms_streams

__all__ = [
    "StreamId", "StreamRef", "StreamSignal", "SubscriptionHandle",
    "StreamProvider", "batch_consumer",
    "SMSStreamProvider", "add_sms_streams",
    "QueueAdapter", "QueueReceiver", "QueueBatch", "MemoryQueueAdapter",
    "GeneratorQueueAdapter",
    "DurableQueueAdapter", "FileQueueAdapter", "SqliteQueueAdapter",
    "PersistentStreamProvider", "add_persistent_streams",
    "PubSubRendezvousGrain", "implicit_stream_subscription",
    "QueueBalancer", "DeploymentBasedBalancer", "BestFitBalancer",
    "LeaseBasedBalancer", "MemoryLeaseProvider",
    "PooledQueueCache", "QueueCacheCursor",
    "DeviceStreamProvider", "DeviceSubscription", "add_device_streams",
]
