"""Persistent (queue-backed) streams: adapters, balancer, pulling agents.

Re-design of /root/reference/src/Orleans.Runtime/Streams/PersistentStream/:
``PersistentStreamPullingAgent.cs:13`` (timer-driven pull loop :141, read
:350-368, per-consumer delivery with backoff retry + IStreamFailureHandler),
``PersistentStreamPullingManager.cs:14`` (queue↔silo assignment), the
``IQueueAdapter`` abstraction (Core/Streams/PersistentStreams/), the
membership-driven ``DeploymentBasedQueueBalancer.cs:40``, and the Memory
adapter (OrleansProviders/Streams/Memory/MemoryAdapterFactory.cs:22 — there
backed by MemoryStreamQueueGrain; here a shared in-proc queue object standing
in for the external queue service).
"""

from __future__ import annotations

import asyncio
import collections
import logging
from dataclasses import dataclass
from typing import TYPE_CHECKING, Callable

from ..core.ids import SiloAddress, stable_hash64
from .core import StreamId, StreamProvider, SubscriptionHandle
from .pubsub import PubSubRendezvousGrain, deliver_to_consumer, resolve_consumers

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.streams.persistent")

__all__ = [
    "QueueBatch", "QueueAdapter", "QueueReceiver", "MemoryQueueAdapter",
    "PersistentStreamProvider", "PullingManager", "add_persistent_streams",
]


@dataclass
class QueueBatch:
    """One queued batch (IBatchContainer): events of one stream + cursor."""

    stream: StreamId
    items: list
    seq: int


class QueueAdapter:
    """External-queue abstraction (IQueueAdapter)."""

    name = "queue"
    n_queues = 8

    async def queue_message_batch(self, queue_id: int, stream: StreamId,
                                  items: list) -> None:
        raise NotImplementedError

    def create_receiver(self, queue_id: int) -> "QueueReceiver":
        raise NotImplementedError


class QueueReceiver:
    """Per-queue pull handle (IQueueAdapterReceiver)."""

    async def get_messages(self, max_count: int) -> list[QueueBatch]:
        raise NotImplementedError

    async def ack(self, batch: QueueBatch) -> None:  # noqa: B027
        pass


class MemoryQueueAdapter(QueueAdapter):
    """In-proc shared queue bank: the dev/test "external queue service".
    One instance must be shared by every silo of the cluster (like a real
    queue service endpoint)."""

    def __init__(self, n_queues: int = 8, name: str = "memory"):
        self.name = name
        self.n_queues = n_queues
        self._queues: list[collections.deque[QueueBatch]] = [
            collections.deque() for _ in range(n_queues)]
        self._seq = 0

    async def queue_message_batch(self, queue_id, stream, items) -> None:
        self._seq += 1
        self._queues[queue_id].append(QueueBatch(stream, list(items), self._seq))

    def create_receiver(self, queue_id: int) -> "QueueReceiver":
        return _MemoryReceiver(self._queues[queue_id])


class _MemoryReceiver(QueueReceiver):
    def __init__(self, queue: collections.deque):
        self._queue = queue
        self._inflight: list[QueueBatch] = []

    async def get_messages(self, max_count: int) -> list[QueueBatch]:
        out = []
        while self._queue and len(out) < max_count:
            out.append(self._queue.popleft())
        # keep a separate inflight list: ack() mutates it while the agent
        # iterates the returned list
        self._inflight = list(out)
        return out

    async def ack(self, batch: QueueBatch) -> None:
        if batch in self._inflight:
            self._inflight.remove(batch)


def deployment_balancer(queue_id: int, adapter_name: str,
                        silos: list[SiloAddress]) -> SiloAddress | None:
    """Queue→silo assignment by consistent hash over the alive set
    (DeploymentBasedQueueBalancer.cs:40 — deterministic, membership-driven,
    no coordination needed: every silo computes the same mapping)."""
    if not silos:
        return None
    # rendezvous (highest-random-weight) hashing: minimal churn on join/leave
    return min(silos, key=lambda s: stable_hash64(
        f"qb|{adapter_name}|{queue_id}|{s.endpoint}|{s.generation}"))


class PullingAgent:
    """One owned queue's pump (PersistentStreamPullingAgent.cs:13): pull a
    batch, resolve subscribers, deliver in order with bounded backoff retry,
    then ack. A small bounded cache of recent batches supports diagnostics
    (the SimpleQueueCache stand-in)."""

    def __init__(self, provider: "PersistentStreamProvider", queue_id: int,
                 pull_period: float, max_batch: int,
                 max_delivery_attempts: int = 3, cache_size: int = 1024):
        self.provider = provider
        self.queue_id = queue_id
        self.pull_period = pull_period
        self.max_batch = max_batch
        self.max_delivery_attempts = max_delivery_attempts
        self.receiver = provider.adapter.create_receiver(queue_id)
        self.cache: collections.deque[QueueBatch] = collections.deque(
            maxlen=cache_size)
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _run(self) -> None:
        silo = self.provider.silo
        while True:
            try:
                batches = await self.receiver.get_messages(self.max_batch)
            except Exception:  # noqa: BLE001
                log.exception("queue %d read failed", self.queue_id)
                batches = []
            if not batches:
                await asyncio.sleep(self.pull_period)
                continue
            for batch in batches:
                self.cache.append(batch)
                silo.stats.increment("streams.persistent.pulled",
                                     len(batch.items))
                await self._deliver_batch(batch)
                await self.receiver.ack(batch)

    async def _deliver_batch(self, batch: QueueBatch) -> None:
        silo = self.provider.silo
        try:
            consumers = await resolve_consumers(silo, batch.stream)
        except Exception:  # noqa: BLE001
            log.exception("pubsub resolve failed for %s", batch.stream)
            return
        for handle in consumers:
            backoff = 0.05
            for attempt in range(self.max_delivery_attempts):
                try:
                    await deliver_to_consumer(
                        silo, handle, batch.items, batch.seq)
                    break
                except Exception as exc:  # noqa: BLE001
                    if attempt + 1 == self.max_delivery_attempts:
                        self.provider.on_delivery_failure(
                            handle, batch.stream, batch, exc)
                    else:
                        await asyncio.sleep(backoff)
                        backoff *= 2


class PullingManager:
    """Per-silo agent manager (PersistentStreamPullingManager.cs:14):
    recomputes owned queues from the membership view and starts/stops
    agents on re-balance."""

    def __init__(self, provider: "PersistentStreamProvider",
                 rebalance_period: float = 2.0):
        self.provider = provider
        self.rebalance_period = rebalance_period
        self.agents: dict[int, PullingAgent] = {}
        self._task: asyncio.Task | None = None
        self._kick = asyncio.Event()

    def start(self) -> None:
        silo = self.provider.silo
        if silo.membership is not None:
            silo.membership.subscribe(lambda a, d: self._kick.set())
        self._task = asyncio.get_running_loop().create_task(self._loop())
        self._kick.set()

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        for agent in self.agents.values():
            agent.stop()
        self.agents.clear()

    async def _loop(self) -> None:
        while True:
            try:
                await asyncio.wait_for(self._kick.wait(),
                                       timeout=self.rebalance_period)
            except asyncio.TimeoutError:
                pass
            self._kick.clear()
            try:
                self._rebalance()
            except Exception:  # noqa: BLE001
                log.exception("stream queue rebalance failed")

    def _rebalance(self) -> None:
        p = self.provider
        me = p.silo.silo_address
        alive = p.silo.locator.alive_list
        mine = {q for q in range(p.adapter.n_queues)
                if deployment_balancer(q, p.adapter.name, alive) == me}
        for q in list(self.agents):
            if q not in mine:
                self.agents.pop(q).stop()
        for q in mine:
            if q not in self.agents:
                agent = PullingAgent(p, q, p.pull_period, p.max_batch)
                agent.start()
                self.agents[q] = agent


class PersistentStreamProvider(StreamProvider):
    """Queue-backed provider (PersistentStreamProvider.cs)."""

    def __init__(self, silo: "Silo", name: str, adapter: QueueAdapter,
                 pull_period: float = 0.1, max_batch: int = 32,
                 failure_handler: Callable | None = None):
        super().__init__(silo, name)
        self.adapter = adapter
        self.pull_period = pull_period
        self.max_batch = max_batch
        self.failure_handler = failure_handler
        self.manager = PullingManager(self)

    async def produce(self, stream: StreamId, items: list) -> None:
        queue_id = stream.uniform_hash % self.adapter.n_queues
        self.silo.stats.increment("streams.persistent.produced", len(items))
        await self.adapter.queue_message_batch(queue_id, stream, items)

    async def register_consumer(self, handle: SubscriptionHandle) -> None:
        await self._rendezvous(handle.stream).register_consumer(handle)

    async def unregister_consumer(self, handle: SubscriptionHandle) -> None:
        await self._rendezvous(handle.stream).unregister_consumer(
            handle.handle_id)

    async def consumer_handles(self, stream: StreamId):
        return await resolve_consumers(self.silo, stream)

    def on_delivery_failure(self, handle: SubscriptionHandle,
                            stream: StreamId, batch: QueueBatch,
                            exc: BaseException) -> None:
        """IStreamFailureHandler: called after delivery retries exhaust."""
        self.silo.stats.increment("streams.persistent.delivery_failures")
        if self.failure_handler is not None:
            self.failure_handler(handle, stream, batch, exc)
        else:
            log.warning("dropping %d events of %s for %s after retries: %s",
                        len(batch.items), stream, handle.grain_id, exc)

    def _rendezvous(self, stream: StreamId):
        return self.silo.grain_factory.get_grain(
            PubSubRendezvousGrain, str(stream))


def add_persistent_streams(builder, name: str, adapter: QueueAdapter,
                           **kw):
    """Register a queue-backed provider on a SiloBuilder. ``adapter`` must
    be the cluster-shared queue object (the external queue service)."""
    builder.add_grains(PubSubRendezvousGrain)

    def install(silo) -> None:
        provider = PersistentStreamProvider(silo, name, adapter, **kw)
        silo.stream_providers[name] = provider
        from ..runtime.silo import ServiceLifecycleStage
        silo.subscribe_lifecycle(
            ServiceLifecycleStage.RUNTIME_GRAIN_SERVICES,
            provider.manager.start, provider.manager.stop)

    return builder.configure(install)
