"""Persistent (queue-backed) streams: adapters, balancer, pulling agents.

Re-design of /root/reference/src/Orleans.Runtime/Streams/PersistentStream/:
``PersistentStreamPullingAgent.cs:13`` (timer-driven pull loop :141, read
:350-368, per-consumer delivery with backoff retry + IStreamFailureHandler),
``PersistentStreamPullingManager.cs:14`` (queue↔silo assignment), the
``IQueueAdapter`` abstraction (Core/Streams/PersistentStreams/), the
membership-driven ``DeploymentBasedQueueBalancer.cs:40``, and the Memory
adapter (OrleansProviders/Streams/Memory/MemoryAdapterFactory.cs:22 — there
backed by MemoryStreamQueueGrain; here a shared in-proc queue object standing
in for the external queue service).
"""

from __future__ import annotations

import asyncio
import collections
import logging
from dataclasses import dataclass
from typing import TYPE_CHECKING, Callable

from ..core.asyncs import ExponentialBackoff, retry
from ..core.errors import StreamError
from .balancer import DeploymentBasedBalancer, QueueBalancer
from .cache import PooledQueueCache
from .core import StreamId, StreamProvider, SubscriptionHandle
from .pubsub import PubSubRendezvousGrain, deliver_to_consumer, resolve_consumers

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.streams.persistent")

__all__ = [
    "QueueBatch", "QueueAdapter", "QueueReceiver", "MemoryQueueAdapter",
    "GeneratorQueueAdapter",
    "PersistentStreamProvider", "PullingManager", "PullingAgent",
    "add_persistent_streams",
]


@dataclass
class QueueBatch:
    """One queued batch (IBatchContainer): events of one stream + cursor."""

    stream: StreamId
    items: list
    seq: int


class QueueAdapter:
    """External-queue abstraction (IQueueAdapter)."""

    name = "queue"
    n_queues = 8

    async def queue_message_batch(self, queue_id: int, stream: StreamId,
                                  items: list) -> None:
        raise NotImplementedError

    def create_receiver(self, queue_id: int) -> "QueueReceiver":
        raise NotImplementedError


class QueueReceiver:
    """Per-queue pull handle (IQueueAdapterReceiver)."""

    async def get_messages(self, max_count: int) -> list[QueueBatch]:
        raise NotImplementedError

    async def ack(self, batch: QueueBatch) -> None:  # noqa: B027
        pass

    def shutdown(self) -> None:  # noqa: B027
        """Abandon the receiver: unacked batches must become visible to the
        queue's next owner (IQueueAdapterReceiver.Shutdown — at-least-once
        across queue-ownership handoff)."""


class MemoryQueueAdapter(QueueAdapter):
    """In-proc shared queue bank: the dev/test "external queue service".
    One instance must be shared by every silo of the cluster (like a real
    queue service endpoint)."""

    def __init__(self, n_queues: int = 8, name: str = "memory"):
        self.name = name
        self.n_queues = n_queues
        self._queues: list[collections.deque[QueueBatch]] = [
            collections.deque() for _ in range(n_queues)]
        self._seq = 0

    async def queue_message_batch(self, queue_id, stream, items) -> None:
        # item-cumulative sequence: batch.seq is the token of the batch's
        # FIRST item, so per-item tokens (seq + i) are unique and ordered
        # across batches — the EventSequenceToken contract consumers dedup
        # and rewind by (per-batch numbering made tokens of adjacent
        # multi-item batches overlap)
        seq = self._seq
        self._seq += len(items)
        self._queues[queue_id].append(QueueBatch(stream, list(items), seq))

    def create_receiver(self, queue_id: int) -> "QueueReceiver":
        return _MemoryReceiver(self._queues[queue_id])


class _MemoryReceiver(QueueReceiver):
    def __init__(self, queue: collections.deque):
        self._queue = queue
        # ALL delivered-but-unacked batches, across pulls — acks may arrive
        # long after later pulls (cursor-paced consumers)
        self._inflight: list[QueueBatch] = []

    async def get_messages(self, max_count: int) -> list[QueueBatch]:
        out = []
        while self._queue and len(out) < max_count:
            out.append(self._queue.popleft())
        self._inflight.extend(out)
        return out

    async def ack(self, batch: QueueBatch) -> None:
        if batch in self._inflight:
            self._inflight.remove(batch)

    def shutdown(self) -> None:
        """Return unacked batches to the head of the shared queue (in order)
        so the queue's next owner redelivers them."""
        for batch in reversed(self._inflight):
            self._queue.appendleft(batch)
        self._inflight.clear()


class GeneratorQueueAdapter(QueueAdapter):
    """Self-generating adapter — the reference's Generator stream provider
    (OrleansProviders/Streams/Generator/GeneratorAdapter.cs: streams
    synthesized inside the receiver, no external queue), used for load
    and failure-injection testing of the pulling machinery.

    ``generate(queue_id, poll_index)`` returns ``(StreamId, items)`` for
    the next batch, or ``None`` when that queue is exhausted. Sequence
    tokens are item-cumulative per queue and namespaced by a per-queue
    stride, so tokens from different queues can never collide (a
    generator that emits one StreamId from several queues still gets
    distinct tokens; keep a stream on one queue if rewind offsets should
    be contiguous). A regenerated receiver (queue-ownership handoff)
    restarts its sequence — deterministic regeneration is the adapter's
    purpose, matching the reference's Generator provider. Producing into
    this adapter is an error — the generator is the only source."""

    def __init__(self, generate, n_queues: int = 4, name: str = "generator"):
        self.name = name
        self.n_queues = n_queues
        self._generate = generate

    async def queue_message_batch(self, queue_id, stream, items) -> None:
        raise StreamError(
            "GeneratorQueueAdapter synthesizes its own batches; "
            "on_next/on_next_batch cannot produce into it")

    def create_receiver(self, queue_id: int) -> "QueueReceiver":
        return _GeneratorReceiver(self._generate, queue_id)


_GENERATOR_TOKEN_STRIDE = 1 << 32


class _GeneratorReceiver(QueueReceiver):
    def __init__(self, generate, queue_id: int):
        self._generate = generate
        self._queue_id = queue_id
        self._poll = 0
        self._seq = queue_id * _GENERATOR_TOKEN_STRIDE
        self._done = False

    async def get_messages(self, max_count: int) -> list[QueueBatch]:
        out: list[QueueBatch] = []
        while not self._done and len(out) < max_count:
            produced = self._generate(self._queue_id, self._poll)
            self._poll += 1
            if produced is None:
                self._done = True
                break
            stream, items = produced
            out.append(QueueBatch(stream, list(items), self._seq))
            self._seq += len(items)
        return out


class _ConsumerPump:
    """One consumer's delivery loop over the agent's cache: an independent
    cursor + serial task, so a slow consumer throttles only itself (and,
    via cache pressure, the pull) — never other consumers."""

    def __init__(self, agent: "PullingAgent", stream: StreamId, handle):
        self.agent = agent
        self.stream = stream
        self.handle = handle
        self.key = (stream, handle.handle_id)
        self.cursor = agent.cache.new_cursor(self.key, from_oldest=True)
        self.wake = asyncio.Event()
        self.task = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        agent = self.agent
        await self._replay_durable_history()
        while True:
            # clear BEFORE checking so a set() racing the check is kept
            self.wake.clear()
            cb = self._next_mine()
            if cb is None:
                await agent.evict_and_ack()  # yields: new batches may land
                cb = self._next_mine()
                if cb is None:
                    await self.wake.wait()
                    continue
            end = cb.batch.seq + len(cb.batch.items)
            if end <= agent.provider.replay_progress.get(self.key, 0):
                # fully below the recorded progress floor: this batch was
                # already delivered by durable-history replay (or an
                # earlier pump incarnation) — skip the avoidable duplicate
                continue
            await self._deliver(cb.batch)
            agent.provider.note_replay_progress(self.key, end)

    async def _replay_durable_history(self) -> None:
        """Rewind beyond the in-memory cache window: a subscription with a
        ``from_token`` older than anything cached replays ACKED batches
        from the durable queue log (the EventHub-offset retention replay;
        durable.DurableQueueAdapter.replay). Only acked batches: unacked
        ones redeliver through the normal pull, and this pump's cursor —
        created from_oldest BEFORE this runs — pins eviction, so no batch
        can slip between replay and the cache (at-least-once holds; cache
        batches overlapping what replay already delivered are skipped by
        the replay-progress floor in the delivery loop).

        The replay floor is max(subscription token, this silo's recorded
        delivery progress for the consumer): pumps are recreated on every
        queue rebalance / consumer-view churn, and without the progress
        floor each recreation would re-deliver the full retained history.
        Progress is silo-local — a queue handed to ANOTHER silo replays
        from the subscription token again (at-least-once; consumers dedup
        by token)."""
        ft = getattr(self.handle, "from_token", None)
        replay = getattr(self.agent.provider.adapter, "replay", None)
        if ft is None or replay is None:
            return
        progress = self.agent.provider.replay_progress
        floor = max(ft, progress.get(self.key, ft))
        try:
            history = await replay(self.stream, floor)
        except Exception:  # noqa: BLE001 — replay is best-effort recovery
            log.exception("durable replay failed for %s", self.stream)
            return
        for batch in sorted(history, key=lambda b: b.seq):
            await self._deliver(batch)
            self.agent.provider.note_replay_progress(
                self.key, batch.seq + len(batch.items))

    def _next_mine(self):
        """Advance past other streams' batches to the next batch of ours."""
        while True:
            cb = self.agent.cache.next(self.cursor)
            if cb is None or cb.batch.stream == self.stream:
                return cb

    async def _deliver(self, batch: QueueBatch) -> None:
        silo = self.agent.provider.silo
        # shared across retry attempts: a mid-batch failure resumes at the
        # failed item instead of re-applying delivered ones
        progress: dict = {}
        try:
            await retry(
                lambda: deliver_to_consumer(
                    silo, self.handle, batch.items, batch.seq, progress),
                max_attempts=self.agent.max_delivery_attempts,
                backoff=ExponentialBackoff(min_delay=0.05, max_delay=2.0))
        except Exception as exc:  # noqa: BLE001 — retries exhausted
            self.agent.provider.on_delivery_failure(
                self.handle, self.stream, batch, exc)

    def stop(self) -> None:
        self.agent.cache.remove_cursor(self.key)
        self.task.cancel()


class PullingAgent:
    """One owned queue's pump (PersistentStreamPullingAgent.cs:13): pull
    into a cursor-based PooledQueueCache, fan out via independent
    per-consumer pumps, ack batches upstream only once every cursor has
    passed them, and pause pulling while the cache is under pressure —
    slow consumers throttle the pull instead of forcing redelivery."""

    def __init__(self, provider: "PersistentStreamProvider", queue_id: int,
                 pull_period: float, max_batch: int,
                 max_delivery_attempts: int = 3, cache_capacity: int = 256,
                 consumer_refresh_period: float = 1.0):
        self.provider = provider
        self.queue_id = queue_id
        self.pull_period = pull_period
        self.max_batch = max_batch
        self.max_delivery_attempts = max_delivery_attempts
        self.consumer_refresh_period = consumer_refresh_period
        self.receiver = provider.adapter.create_receiver(queue_id)
        self.cache = PooledQueueCache(capacity=cache_capacity)
        self.pumps: dict[tuple, _ConsumerPump] = {}
        self._streams_seen: dict[StreamId, float] = {}  # stream -> last refresh
        self._stream_activity: dict[StreamId, float] = {}  # stream -> last batch
        self.stream_idle_ttl = 5 * consumer_refresh_period
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        for pump in self.pumps.values():
            pump.stop()
        self.pumps.clear()
        # hand unacked work back to the queue for the next owner
        try:
            self.receiver.shutdown()
        except Exception:  # noqa: BLE001
            log.exception("receiver shutdown failed for queue %d",
                          self.queue_id)

    async def _run(self) -> None:
        silo = self.provider.silo
        loop = asyncio.get_running_loop()
        while True:
            if self.cache.under_pressure:
                # backpressure: the slowest consumer gates the pull
                # (SimpleQueueCache under-pressure semantics)
                await asyncio.sleep(self.pull_period)
                await self.evict_and_ack()
                continue
            try:
                batches = await self.receiver.get_messages(self.max_batch)
            except Exception:  # noqa: BLE001
                log.exception("queue %d read failed", self.queue_id)
                batches = []
            for batch in batches:
                self.cache.add(batch)
                silo.stats.increment("streams.persistent.pulled",
                                     len(batch.items))
            streams = {b.stream for b in batches}
            now = loop.time()
            for stream in streams:
                self._stream_activity[stream] = now
            # refresh pub-sub views for streams that are new or stale;
            # prune streams gone idle with no consumers and nothing cached
            # (the agent's stream-TTL purge — otherwise dead streams are
            # re-resolved forever)
            cached_streams = self.cache.cached_streams() \
                if len(streams) < len(self._streams_seen) else set()
            for stream in list(self._streams_seen):
                if now - self._streams_seen[stream] \
                        > self.consumer_refresh_period:
                    has_pump = any(k[0] == stream for k in self.pumps)
                    idle = now - self._stream_activity.get(stream, now) \
                        > self.stream_idle_ttl
                    if idle and not has_pump and stream not in cached_streams:
                        self._streams_seen.pop(stream, None)
                        self._stream_activity.pop(stream, None)
                        # a reappearing stream must re-pin eviction until
                        # its consumer view is re-resolved
                        self.cache.resolved_streams.discard(stream)
                    else:
                        streams.add(stream)
            for stream in streams:
                await self._refresh_consumers(stream, now)
            if batches:
                for pump in self.pumps.values():
                    pump.wake.set()
            else:
                await asyncio.sleep(self.pull_period)

    async def _refresh_consumers(self, stream: StreamId, now: float) -> None:
        """Reconcile per-consumer pumps with the pub-sub view
        (the agent's AddSubscriber/RemoveSubscriber path)."""
        self._streams_seen[stream] = now
        try:
            handles = await resolve_consumers(self.provider.silo, stream)
        except Exception:  # noqa: BLE001
            log.exception("pubsub resolve failed for %s", stream)
            return
        live = {(stream, h.handle_id) for h in handles}
        for key in [k for k in self.pumps if k[0] == stream and k not in live]:
            self.pumps.pop(key).stop()
            # the subscription itself is gone (pubsub unregister), not a
            # rebalance-driven pump recreation: its replay floor will never
            # be consulted again — drop it or it leaks per dead handle_id
            self.provider.replay_progress.pop(key, None)
        for h in handles:
            key = (stream, h.handle_id)
            if key not in self.pumps:
                self.pumps[key] = _ConsumerPump(self, stream, h)
                self.pumps[key].wake.set()
        # consumer view now known: cached batches for this stream may be
        # evicted once cursors pass (or immediately, if no consumers) —
        # until this point they pin the cache's eviction floor
        self.cache.resolved_streams.add(stream)

    async def evict_and_ack(self) -> None:
        """Evict fully-consumed batches and ack them upstream — at-least-once
        delivery: a batch leaves the external queue only after every
        consumer cursor has passed it. Acks are independent (each marks a
        distinct seq) and issue concurrently so a group-committing
        durable backend coalesces them into shared fsyncs."""
        purged = self.cache.purge()
        if not purged:
            return
        results = await asyncio.gather(
            *(self.receiver.ack(b) for b in purged),
            return_exceptions=True)
        for r in results:
            if isinstance(r, BaseException):
                log.warning("ack failed for queue %d: %r",
                            self.queue_id, r)


class PullingManager:
    """Per-silo agent manager (PersistentStreamPullingManager.cs:14):
    recomputes owned queues from the membership view and starts/stops
    agents on re-balance."""

    def __init__(self, provider: "PersistentStreamProvider",
                 rebalance_period: float = 2.0):
        self.provider = provider
        self.rebalance_period = rebalance_period
        self.agents: dict[int, PullingAgent] = {}
        self._task: asyncio.Task | None = None
        self._kick = asyncio.Event()

    def start(self) -> None:
        silo = self.provider.silo
        if silo.membership is not None:
            silo.membership.subscribe(lambda a, d: self._kick.set())
        self._task = asyncio.get_running_loop().create_task(self._loop())
        self._kick.set()

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        for agent in self.agents.values():
            agent.stop()
        self.agents.clear()
        self.provider.balancer.close(self.provider.silo.silo_address)

    async def _loop(self) -> None:
        while True:
            try:
                await asyncio.wait_for(self._kick.wait(),
                                       timeout=self.rebalance_period)
            except asyncio.TimeoutError:
                pass
            self._kick.clear()
            try:
                await self._rebalance()
            except Exception:  # noqa: BLE001
                log.exception("stream queue rebalance failed")

    async def _rebalance(self) -> None:
        """Recompute owned queues via the provider's balancer; the loop's
        period doubles as the lease renewal timer for LeaseBasedBalancer."""
        p = self.provider
        me = p.silo.silo_address
        alive = p.silo.locator.alive_list
        mine = await p.balancer.owned_queues(
            p.adapter.n_queues, p.adapter.name, me, alive)
        for q in list(self.agents):
            if q not in mine:
                self.agents.pop(q).stop()
        for q in mine:
            if q not in self.agents:
                agent = PullingAgent(
                    p, q, p.pull_period, p.max_batch,
                    max_delivery_attempts=p.max_delivery_attempts,
                    cache_capacity=p.cache_capacity)
                agent.start()
                self.agents[q] = agent


class PersistentStreamProvider(StreamProvider):
    """Queue-backed provider (PersistentStreamProvider.cs)."""

    def __init__(self, silo: "Silo", name: str, adapter: QueueAdapter,
                 pull_period: float = 0.1, max_batch: int = 32,
                 failure_handler: Callable | None = None,
                 balancer: "QueueBalancer | None" = None,
                 cache_capacity: int = 256,
                 rebalance_period: float = 2.0,
                 max_delivery_attempts: int = 3):
        super().__init__(silo, name)
        self.adapter = adapter
        self.pull_period = pull_period
        self.max_batch = max_batch
        # per-batch delivery retries before the failure handler takes the
        # batch (StreamPubSubMatch retry discipline): size this to outlast
        # expected partition/failover windows when zero loss is required
        self.max_delivery_attempts = max_delivery_attempts
        self.failure_handler = failure_handler
        self.balancer = balancer or DeploymentBasedBalancer()
        self.cache_capacity = cache_capacity
        self.manager = PullingManager(self, rebalance_period=rebalance_period)
        # silo-local delivery progress per (stream, handle_id): the floor
        # for durable-history replay across pump recreations. Entries for
        # unsubscribed handles are dropped at pump reconciliation; the LRU
        # cap below catches handles removed while this silo did not own
        # the queue (losing a floor only re-delivers — at-least-once holds)
        self.replay_progress: dict[tuple, int] = {}

    _REPLAY_PROGRESS_CAP = 4096

    def note_replay_progress(self, key: tuple, end: int) -> None:
        """Raise the delivery floor for (stream, handle_id); re-insertion
        keeps the dict ordered by last update so the cap evicts the
        longest-idle floors first."""
        prog = self.replay_progress
        cur = prog.pop(key, 0)
        prog[key] = max(cur, end)
        while len(prog) > self._REPLAY_PROGRESS_CAP:
            prog.pop(next(iter(prog)))

    async def produce(self, stream: StreamId, items: list) -> None:
        queue_id = stream.uniform_hash % self.adapter.n_queues
        await self.adapter.queue_message_batch(queue_id, stream, items)
        # count AFTER the adapter accepts: a rejecting adapter (e.g. the
        # generator provider) must not inflate the produced counter
        self.silo.stats.increment("streams.persistent.produced", len(items))

    async def register_consumer(self, handle: SubscriptionHandle) -> None:
        await self._rendezvous(handle.stream).register_consumer(handle)

    async def unregister_consumer(self, handle: SubscriptionHandle) -> None:
        await self._rendezvous(handle.stream).unregister_consumer(
            handle.handle_id)

    async def consumer_handles(self, stream: StreamId):
        return await resolve_consumers(self.silo, stream)

    def on_delivery_failure(self, handle: SubscriptionHandle,
                            stream: StreamId, batch: QueueBatch,
                            exc: BaseException) -> None:
        """IStreamFailureHandler: called after delivery retries exhaust."""
        self.silo.stats.increment("streams.persistent.delivery_failures")
        if self.failure_handler is not None:
            self.failure_handler(handle, stream, batch, exc)
        else:
            log.warning("dropping %d events of %s for %s after retries: %s",
                        len(batch.items), stream, handle.grain_id, exc)

    def _rendezvous(self, stream: StreamId):
        return self.silo.grain_factory.get_grain(
            PubSubRendezvousGrain, str(stream))


def add_persistent_streams(builder, name: str, adapter: QueueAdapter,
                           **kw):
    """Register a queue-backed provider on a SiloBuilder. ``adapter`` must
    be the cluster-shared queue object (the external queue service)."""
    builder.add_grains(PubSubRendezvousGrain)

    def install(silo) -> None:
        provider = PersistentStreamProvider(silo, name, adapter, **kw)
        silo.stream_providers[name] = provider
        from ..runtime.silo import ServiceLifecycleStage
        silo.subscribe_lifecycle(
            ServiceLifecycleStage.RUNTIME_GRAIN_SERVICES,
            provider.manager.start, provider.manager.stop)

    return builder.configure(install)
