"""Pooled queue cache: cursor-based batch cache with backpressure.

Re-design of /root/reference/src/OrleansProviders/Streams/Common/PooledCache/
``PooledQueueCache.cs:386`` (cursor iteration over cached message blocks) and
``SimpleCache/SimpleQueueCache.cs:328`` (bounded cache + under-pressure
signal). Each pulling agent owns one cache: pulled batches are appended once
and consumed by any number of per-consumer cursors at independent speeds; a
batch is evicted (and acked upstream) only once every cursor has passed it;
the pull loop pauses while the cache is under pressure — slow consumers
throttle the pull instead of forcing redelivery.
"""

from __future__ import annotations

import collections
from dataclasses import dataclass, field
from typing import Any

__all__ = ["CachedBatch", "QueueCacheCursor", "PooledQueueCache"]


@dataclass
class CachedBatch:
    """One cached queue batch + delivery bookkeeping."""

    batch: Any  # QueueBatch
    token: int  # monotonically increasing cache position


@dataclass
class QueueCacheCursor:
    """One consumer's read position (the IQueueCacheCursor analog)."""

    consumer_key: Any
    next_token: int
    invalidated: bool = field(default=False)


class PooledQueueCache:
    """Bounded FIFO of batches with multi-cursor consumption."""

    def __init__(self, capacity: int = 256,
                 pressure_threshold: float = 0.75):
        self.capacity = capacity
        self.pressure_threshold = pressure_threshold
        self._items: collections.deque[CachedBatch] = collections.deque()
        self._next_token = 0
        self.cursors: dict[Any, QueueCacheCursor] = {}
        # streams whose consumer view the agent has resolved (with or
        # without consumers). Batches of an UNRESOLVED stream pin the
        # eviction floor: their pump/cursor may simply not exist yet, and
        # evicting them would silently drop events (the bug class this
        # guards: pressure-branch purge racing the first consumer
        # refresh). Maintained by the pulling agent.
        self.resolved_streams: set = set()

    # -- write side --------------------------------------------------------
    def add(self, batch: Any) -> CachedBatch:
        cb = CachedBatch(batch=batch, token=self._next_token)
        self._next_token += 1
        self._items.append(cb)
        return cb

    @property
    def under_pressure(self) -> bool:
        """SimpleQueueCache's IsUnderPressure: the pull loop must pause when
        the slowest cursor lags this far behind."""
        return len(self._items) >= self.capacity * self.pressure_threshold

    @property
    def count(self) -> int:
        return len(self._items)

    @property
    def write_token(self) -> int:
        """The token the NEXT added batch will take — the write head a
        cursor-lag gauge measures against (tokens are contiguous, so
        ``write_token - cursor.next_token`` is the lag in batches)."""
        return self._next_token

    def cached_streams(self) -> set:
        """Distinct stream ids with batches still cached."""
        return {cb.batch.stream for cb in self._items}

    # -- cursor side -------------------------------------------------------
    def new_cursor(self, consumer_key: Any,
                   from_oldest: bool = True) -> QueueCacheCursor:
        """Create (or reset) a consumer cursor. ``from_oldest`` starts at the
        oldest cached batch; otherwise at the next batch to arrive."""
        if from_oldest and self._items:
            token = self._items[0].token
        else:
            token = self._next_token
        cur = QueueCacheCursor(consumer_key=consumer_key, next_token=token)
        self.cursors[consumer_key] = cur
        return cur

    def remove_cursor(self, consumer_key: Any) -> None:
        self.cursors.pop(consumer_key, None)

    def next(self, cursor: QueueCacheCursor) -> CachedBatch | None:
        """The batch at the cursor, advancing it; None when drained.
        Tokens are contiguous, so the deque position is head-relative
        arithmetic — O(1), not a scan."""
        if cursor.invalidated or not self._items:
            return None
        head = self._items[0].token
        idx = max(0, cursor.next_token - head)
        if idx >= len(self._items):
            return None
        cb = self._items[idx]
        cursor.next_token = cb.token + 1
        return cb

    # -- eviction ----------------------------------------------------------
    def purge(self) -> list[Any]:
        """Evict batches every live cursor has passed; returns the evicted
        batches (the agent acks them upstream). A stream the agent has not
        yet resolved consumers for pins the floor at its oldest batch —
        see ``resolved_streams``. With no cursors and everything resolved
        the cache drains fully — no consumers means nothing to wait for."""
        if self.cursors:
            low = min(c.next_token for c in self.cursors.values())
        else:
            low = self._next_token
        for cb in self._items:
            if cb.token >= low:
                break
            if cb.batch.stream not in self.resolved_streams:
                # tokens are ordered: the first unresolved batch is the floor
                low = cb.token
                break
        evicted = []
        while self._items and self._items[0].token < low:
            evicted.append(self._items.popleft().batch)
        return evicted
