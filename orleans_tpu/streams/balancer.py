"""Stream queue balancers: queue→silo assignment strategies.

Re-design of /root/reference/src/Orleans.Runtime/Streams/QueueBalancer/:
``DeploymentBasedQueueBalancer.cs:40`` (membership-driven deterministic
assignment), ``BestFitBalancer.cs`` (even-count distribution),
``LeaseBasedQueueBalancer.cs:80`` (lease-table ownership with TTL renewal —
there backed by Azure blob leases; here a pluggable LeaseProvider with an
in-memory dev implementation, the MemoryQueueAdapter analog).
"""

from __future__ import annotations

import time
from typing import Protocol

from ..core.ids import SiloAddress, stable_hash64

__all__ = [
    "QueueBalancer", "DeploymentBasedBalancer", "BestFitBalancer",
    "LeaseProvider", "MemoryLeaseProvider", "LeaseBasedBalancer",
]


class QueueBalancer(Protocol):
    """Strategy deciding which of ``n_queues`` this silo should pump.
    Deterministic balancers need no coordination (every silo computes the
    same mapping from the shared membership view); lease-based balancers
    coordinate through an external lease store."""

    async def owned_queues(self, n_queues: int, adapter_name: str,
                           me: SiloAddress,
                           alive: list[SiloAddress]) -> set[int]: ...

    def close(self, me: SiloAddress) -> None: ...


class DeploymentBasedBalancer:
    """Rendezvous (highest-random-weight) hashing over the alive set
    (DeploymentBasedQueueBalancer.cs:40): deterministic, membership-driven,
    minimal churn on join/leave."""

    async def owned_queues(self, n_queues, adapter_name, me, alive):
        if not alive:
            return set()
        return {
            q for q in range(n_queues)
            if min(alive, key=lambda s: stable_hash64(
                f"qb|{adapter_name}|{q}|{s.endpoint}|{s.generation}")) == me}

    def close(self, me: SiloAddress) -> None:  # noqa: B027
        pass


class BestFitBalancer:
    """Strictly even distribution (BestFitBalancer.cs): sort silos and
    queues deterministically and give each silo a contiguous ⌈n/k⌉/⌊n/k⌋
    block. Guarantees per-silo counts differ by at most one — tighter than
    rendezvous hashing — at the cost of more reassignment churn."""

    async def owned_queues(self, n_queues, adapter_name, me, alive):
        if not alive or me not in alive:
            return set()
        ranked = sorted(alive, key=lambda s: (s.endpoint, s.generation))
        k = len(ranked)
        idx = ranked.index(me)
        base, extra = divmod(n_queues, k)
        start = idx * base + min(idx, extra)
        count = base + (1 if idx < extra else 0)
        return set(range(start, start + count))

    def close(self, me: SiloAddress) -> None:  # noqa: B027
        pass


# ---------------------------------------------------------------------------
# Lease-based balancing
# ---------------------------------------------------------------------------

class LeaseProvider(Protocol):
    """External lease store (the ILeaseProvider analog). All silos of a
    cluster must share one store (like a blob container)."""

    def try_acquire(self, key: str, owner: str, ttl: float) -> bool: ...

    def renew(self, key: str, owner: str, ttl: float) -> bool: ...

    def release(self, key: str, owner: str) -> None: ...


class MemoryLeaseProvider:
    """In-proc shared lease table for dev/test clusters."""

    def __init__(self) -> None:
        self._leases: dict[str, tuple[str, float]] = {}  # key -> (owner, expiry)

    def try_acquire(self, key: str, owner: str, ttl: float) -> bool:
        now = time.monotonic()
        cur = self._leases.get(key)
        if cur is not None and cur[1] > now and cur[0] != owner:
            return False
        self._leases[key] = (owner, now + ttl)
        return True

    def renew(self, key: str, owner: str, ttl: float) -> bool:
        cur = self._leases.get(key)
        if cur is None or cur[0] != owner:
            return False
        self._leases[key] = (owner, time.monotonic() + ttl)
        return True

    def release(self, key: str, owner: str) -> None:
        cur = self._leases.get(key)
        if cur is not None and cur[0] == owner:
            self._leases.pop(key, None)


class LeaseBasedBalancer:
    """Lease-table ownership (LeaseBasedQueueBalancer.cs:80): each silo
    tries to hold leases on its fair share of queues; leases expire on silo
    death without any membership round-trip, so queues fail over even if the
    membership oracle lags. Called from the pulling manager's rebalance
    loop, which doubles as the renewal timer."""

    def __init__(self, provider: LeaseProvider, ttl: float = 10.0):
        self.provider = provider
        self.ttl = ttl
        self._held: set[str] = set()

    @staticmethod
    def _owner_id(me: SiloAddress) -> str:
        return f"{me.endpoint}@{me.generation}"

    async def owned_queues(self, n_queues, adapter_name, me, alive):
        owner = self._owner_id(me)
        target = -(-n_queues // max(1, len(alive)))  # fair share, rounded up
        owned: set[int] = set()
        # renew current leases first — losing a held lease mid-stream is the
        # expensive case (another silo starts pumping the same queue)
        for q in range(n_queues):
            key = f"{adapter_name}/{q}"
            if key in self._held:
                if self.provider.renew(key, owner, self.ttl):
                    owned.add(q)
                else:
                    self._held.discard(key)
        # then top up to the fair share from unleased queues
        for q in range(n_queues):
            if len(owned) >= target:
                break
            key = f"{adapter_name}/{q}"
            if q not in owned and self.provider.try_acquire(
                    key, owner, self.ttl):
                self._held.add(key)
                owned.add(q)
        # over-target shedding: give up excess leases so late joiners get
        # their share
        if len(owned) > target:
            for q in sorted(owned, reverse=True)[:len(owned) - target]:
                key = f"{adapter_name}/{q}"
                self.provider.release(key, owner)
                self._held.discard(key)
                owned.discard(q)
        return owned

    def close(self, me: SiloAddress) -> None:
        owner = self._owner_id(me)
        for key in list(self._held):
            self.provider.release(key, owner)
        self._held.clear()
