"""Stream pub-sub: rendezvous grain state + implicit subscriptions.

Re-design of /root/reference/src/Orleans.Runtime/Streams/PubSub/
PubSubRendezvousGrain.cs:21 (RegisterProducer :62, RegisterConsumer :115 —
durable per-stream subscriber sets held in grain state) and
src/Orleans.Core/Streams/PubSub/ImplicitStreamSubscriberTable.cs:11
(attribute-declared subscriptions resolved from the type map, no rendezvous
round-trip).
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING

from ..core.errors import StreamError
from ..core.ids import GrainId, GrainType
from ..runtime.grain import StatefulGrain
from .core import StreamId, StreamSignal, SubscriptionHandle

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.streams.pubsub")

__all__ = ["PubSubRendezvousGrain", "implicit_stream_subscription",
           "implicit_consumers", "resolve_consumers", "deliver_to_consumer"]


class PubSubRendezvousGrain(StatefulGrain):
    """One per stream (key = str(StreamId)): the durable subscriber set."""

    async def register_consumer(self, handle: SubscriptionHandle) -> None:
        self.state.setdefault("consumers", {})[handle.handle_id] = handle
        await self.write_state()

    async def unregister_consumer(self, handle_id: str) -> None:
        if self.state.setdefault("consumers", {}).pop(handle_id, None):
            await self.write_state()

    async def get_consumers(self) -> list[SubscriptionHandle]:
        return list(self.state.get("consumers", {}).values())

    async def register_producer(self, producer: str) -> None:
        if producer not in self.state.setdefault("producers", []):
            self.state["producers"].append(producer)
            await self.write_state()

    async def counts(self) -> tuple[int, int]:
        return (len(self.state.get("producers", [])),
                len(self.state.get("consumers", {})))


def implicit_stream_subscription(namespace: str):
    """Class decorator: auto-subscribe every grain of this class to streams
    in ``namespace``, keyed by the stream key ([ImplicitStreamSubscription]).
    The grain must define ``async def on_next(self, item, token)``."""

    def deco(cls: type) -> type:
        existing = list(getattr(cls, "__implicit_stream_ns__", ()))
        cls.__implicit_stream_ns__ = (*existing, namespace)
        return cls

    return deco


def implicit_consumers(silo: "Silo", stream: StreamId) -> list[SubscriptionHandle]:
    """ImplicitStreamSubscriberTable: registered classes whose declared
    namespaces include this stream's — consumer key = stream key. Device
    tier (VectorGrain) classes participate too: their deliveries ride
    batched kernel ticks (deliver_to_vector_consumer)."""
    out = []
    classes = list(silo.registry.all_classes())
    seen = {c.__name__ for c in classes}
    for vcls in getattr(silo, "vector_interfaces", {}).values():
        if vcls.__name__ not in seen:
            classes.append(vcls)
    vector_names = set(getattr(silo, "vector_interfaces", {}))
    for cls in classes:
        if stream.namespace in getattr(cls, "__implicit_stream_ns__", ()):
            gid = GrainId.for_grain(GrainType.of(cls.__name__), stream.key)
            # host-tier classes that define on_error/on_completed hear
            # producer signals automatically; device-tier (kernel) methods
            # cannot take the signal call shape, so signals skip them
            host = cls.__name__ not in vector_names
            out.append(SubscriptionHandle(
                stream=stream, handle_id=f"implicit:{cls.__name__}",
                grain_id=gid, interface_name=cls.__name__,
                method_name="on_next",
                batch=bool(getattr(getattr(cls, "on_next", None),
                                   "__orleans_stream_batch__", False)),
                error_method_name="on_error"
                if host and callable(getattr(cls, "on_error", None))
                else None,
                completed_method_name="on_completed"
                if host and callable(getattr(cls, "on_completed", None))
                else None))
    return out


def _rendezvous(silo: "Silo", stream: StreamId):
    return silo.grain_factory.get_grain(PubSubRendezvousGrain, str(stream))


async def resolve_consumers(silo: "Silo", stream: StreamId
                            ) -> list[SubscriptionHandle]:
    """Explicit (rendezvous state) + implicit (type map) subscribers."""
    explicit = await _rendezvous(silo, stream).get_consumers()
    return list(explicit) + implicit_consumers(silo, stream)


async def deliver_to_consumer(silo: "Silo", handle: SubscriptionHandle,
                              items: list, first_token: int,
                              progress: dict | None = None) -> None:
    """Deliver events as ordinary grain calls (the consumer-extension path):
    ``await consumer.<method>(item, token)`` per event, in order. Consumers
    that are device-tier (VectorGrain) classes take the batched kernel path
    instead — see :func:`deliver_to_vector_consumer`.

    ``progress`` (per delivery attempt-set, owned by one consumer pump):
    records how many items of this batch were fully delivered, so a retry
    after a mid-batch failure resumes at the failed item instead of
    re-applying the whole batch. Delivery remains at-least-once — the
    failed item itself may have partially applied — matching the
    reference's stream redelivery contract (consumers dedup by token)."""
    if progress is None:
        progress = {}
    from ..observability.tracing import arm_root_link
    # stream deliveries root fresh traces (the pump has no ambient trace):
    # carry the subscribing turn's context as a span link on each new
    # root. Set unconditionally — an unlinked handle must clear whatever
    # a previous delivery armed in this pump task's context.
    arm_root_link(getattr(handle, "link", None))
    ft = getattr(handle, "from_token", None)
    if ft is not None:
        # rewound subscription: trim below the resume token (batches
        # fully before it skip entirely)
        if first_token + len(items) <= ft:
            progress["done"] = len(items)
            return
        if first_token < ft:
            items = items[ft - first_token:]
            first_token = ft
    if any(isinstance(i, StreamSignal) for i in items):
        # signals are produced as their own 1-item batches (on_next
        # rejects them as data), so a signal batch is all-signal; a mixed
        # batch can only come from a hand-built adapter — reject it into
        # the retry/failure-handler path rather than guess an order
        if not all(isinstance(i, StreamSignal) for i in items):
            raise StreamError(
                "stream signals must not be batched with data items")
        for i in range(progress.get("done", 0), len(items)):
            await _deliver_signal(silo, handle, items[i], first_token + i)
            progress["done"] = i + 1
        return
    vcls = silo.vector_interfaces.get(handle.interface_name)
    if vcls is not None and getattr(silo, "vector", None) is not None:
        return await deliver_to_vector_consumer(silo, vcls, handle, items,
                                                progress)
    cls = silo.registry.resolve(handle.interface_name)
    if cls is None:
        raise LookupError(
            f"stream consumer class {handle.interface_name} not registered")
    done = progress.get("done", 0)
    if getattr(handle, "batch", False):
        # batch consumer (IAsyncBatchObserver): one call per queue batch;
        # a retry re-sends the unacknowledged remainder
        await silo.runtime_client.send_request(
            target_grain=handle.grain_id, grain_class=cls,
            interface_name=handle.interface_name,
            method_name=handle.method_name,
            args=(list(items[done:]), first_token + done), kwargs={})
        progress["done"] = len(items)
        return
    for i in range(done, len(items)):
        fut = silo.runtime_client.send_request(
            target_grain=handle.grain_id, grain_class=cls,
            interface_name=handle.interface_name,
            method_name=handle.method_name,
            args=(items[i], first_token + i), kwargs={})
        await fut
        progress["done"] = i + 1


async def _deliver_signal(silo: "Silo", handle: SubscriptionHandle,
                          sig: StreamSignal, token: int) -> None:
    """Route one producer signal to the consumer's dedicated method:
    ``on_error(exc, token)`` / ``on_completed(token)``. A consumer that
    registered no method for this signal kind ignores it (counted), as
    the reference does for a null onErrorAsync delegate."""
    attr = ("error_method_name" if sig.kind == "error"
            else "completed_method_name")
    method = getattr(handle, attr, None)
    if method is None:
        silo.stats.increment(f"streams.signals.{sig.kind}_unhandled")
        log.debug("consumer %s has no %s handler for %s",
                  handle.grain_id, sig.kind, handle.stream)
        return
    cls = silo.registry.resolve(handle.interface_name)
    if cls is None:
        raise LookupError(
            f"stream consumer class {handle.interface_name} not registered")
    args = (sig.error, token) if sig.kind == "error" else (token,)
    silo.stats.increment(f"streams.signals.{sig.kind}_delivered")
    await silo.runtime_client.send_request(
        target_grain=handle.grain_id, grain_class=cls,
        interface_name=handle.interface_name, method_name=method,
        args=args, kwargs={})


async def deliver_to_vector_consumer(silo: "Silo", vcls: type,
                                     handle: SubscriptionHandle,
                                     items: list,
                                     progress: dict | None = None) -> None:
    """Device-tier stream delivery: the pulling agent's per-event host
    turns (PersistentStreamPullingAgent.cs:350-368) become batched kernel
    ticks over the consumer VectorGrain class.

    Item shapes (per QueueBatch item, in order):

    * ``{"keys": [M], "args": {field: [M, ...]}}`` — one ``call_batch``
      tick delivering M events (one per key);
    * ``{"keys": [M], "args_rounds": {field: [K, M, ...]}}`` — one
      scanned ``call_batch_rounds`` kernel delivering K sequential rounds
      to the same keys (K·M events, per-key order preserved);
    * ``{"key": k, <field>: value, ...}`` — a single event; joins the
      runtime's coalescing tick (rt.call), so scalar trickles from many
      streams still share kernel launches.

    Events inside one stream stay ordered: each pump delivers its stream's
    batches sequentially, and rounds are sequential inside the scan.
    """
    import numpy as np

    rt = silo.vector
    method = handle.method_name
    if progress is None:
        progress = {}
    delivered = 0
    for i in range(progress.get("done", 0), len(items)):
        item = items[i]
        if isinstance(item, dict) and "keys" in item:
            delivered += await _deliver_bulk_item(silo, rt, vcls, method,
                                                  item)
        elif isinstance(item, dict) and "key" in item:
            delivered += await _deliver_scalar_item(silo, rt, vcls, method,
                                                    item)
        else:
            raise TypeError(
                f"vector stream item must be a dict with 'keys' (bulk) or "
                f"'key' (single); got {type(item).__name__}")
        progress["done"] = i + 1
    silo.stats.increment("streams.vector.delivered", delivered)


async def _deliver_scalar_item(silo: "Silo", rt, vcls: type, method: str,
                               item: dict) -> int:
    """One scalar event, owner-routed like every other vector call: on the
    key's ring owner it joins the runtime's coalescing tick; elsewhere it
    forwards as a 1-key bulk item (Dispatcher._handle_vector_request's
    single-owner rule — executing on a non-owner would mint divergent
    device state)."""
    import numpy as np

    key = item["key"]
    kwargs = {k: v for k, v in item.items() if k != "key"}
    gid = GrainId.for_grain(GrainType.of(vcls.__name__), key)
    me = silo.silo_address
    owner = silo.locator.ring.owner(gid.uniform_hash) or me
    if owner == me:
        kh = rt.key_hash_for(key, gid.uniform_hash)
        rt.table(vcls).note_route(kh, gid.uniform_hash)
        await rt.call(vcls, kh, method, **kwargs)
        return 1
    sub = {"keys": np.asarray([key]),
           "args": {f: np.asarray([v]) for f, v in kwargs.items()}}
    from ..core.ids import type_code_of
    from ..core.message import Category
    target = GrainId.system_target(type_code_of(VECTOR_STREAM_TARGET), owner)
    await silo.runtime_client.send_request(
        target_grain=target, grain_class=VectorStreamDeliverTarget,
        interface_name="VectorStreamDeliverTarget",
        method_name="vector_stream_deliver",
        args=(vcls.__name__, method, sub), kwargs={},
        target_silo=owner, category=Category.SYSTEM)
    return 1


def _bulk_events(item: dict) -> int:
    import numpy as np

    if "args_rounds" in item:
        K = np.asarray(next(iter(item["args_rounds"].values()))).shape[0]
        return K * len(item["keys"])
    return len(item["keys"])


def _run_bulk_local(rt, vcls: type, method: str, item: dict) -> int:
    import numpy as np

    keys = np.asarray(item["keys"])
    if "args_rounds" in item:
        rt.call_batch_rounds(vcls, method, keys, item["args_rounds"],
                             device_results=True)
    else:
        rt.call_batch(vcls, method, keys, item.get("args", {}),
                      device_results=True)
    return _bulk_events(item)


async def _run_bulk_local_via(silo: "Silo", rt, vcls: type, method: str,
                              item: dict) -> int:
    """The device-fan-out lever (``StreamOptions.device_fanout``): when
    armed, ``{"keys", "args"}`` items whose keys are all dense-regime
    ride the engine's ``stream_fanout`` (broadcast edge exchanges +
    apply_received dedup — tolerates duplicate keys, which call_batch
    lanes cannot) instead of a call_batch tick. Default OFF keeps the
    per-consumer path bit for bit; rounds items and hashed-key items
    always take the existing path."""
    import numpy as np

    if getattr(silo.config, "stream_device_fanout", False) and \
            "args_rounds" not in item:
        keys = np.asarray(item["keys"])
        if keys.dtype.kind in "iu" and keys.size:
            tbl = rt.table(vcls)
            if keys.min() >= 0 and keys.max() < tbl.dense_n:
                return await rt.stream_fanout(
                    vcls, method, keys.astype(np.int64),
                    item.get("args", {}))
    return _run_bulk_local(rt, vcls, method, item)


async def _deliver_bulk_item(silo: "Silo", rt, vcls: type, method: str,
                             item: dict) -> int:
    """Run one bulk item, respecting single-owner routing: in a
    multi-silo cluster each key's device-tier state lives on its ring
    owner (Dispatcher._handle_vector_request), so the item is partitioned
    by owner and remote sub-items ride a system-target hop. The
    single-silo (production TPU-host) case skips partitioning entirely —
    that is the >=1M events/sec path."""
    import numpy as np

    ring = silo.locator.ring
    me = silo.silo_address
    alive = getattr(silo.locator, "alive_list", None) or [me]
    if len(alive) <= 1:
        return await _run_bulk_local_via(silo, rt, vcls, method, item)

    keys = np.asarray(item["keys"])
    cls_type = GrainType.of(vcls.__name__)
    owners = [ring.owner(GrainId.for_grain(cls_type, int(k)).uniform_hash)
              or me for k in keys]
    groups: dict = {}
    for idx, owner in enumerate(owners):
        groups.setdefault(owner, []).append(idx)
    total = 0
    for owner, idxs in groups.items():
        sub = _slice_bulk_item(item, keys, idxs)
        if owner == me:
            total += await _run_bulk_local_via(silo, rt, vcls, method, sub)
        else:
            from ..core.ids import type_code_of
            from ..core.message import Category
            target = GrainId.system_target(
                type_code_of(VECTOR_STREAM_TARGET), owner)
            await silo.runtime_client.send_request(
                target_grain=target, grain_class=VectorStreamDeliverTarget,
                interface_name="VectorStreamDeliverTarget",
                method_name="vector_stream_deliver",
                args=(vcls.__name__, method, sub), kwargs={},
                target_silo=owner, category=Category.SYSTEM)
            total += _bulk_events(sub)
    return total


def _slice_bulk_item(item: dict, keys, idxs: list) -> dict:
    import numpy as np

    sel = np.asarray(idxs)
    sub: dict = {"keys": keys[sel]}
    if "args_rounds" in item:
        sub["args_rounds"] = {f: np.asarray(a)[:, sel]
                              for f, a in item["args_rounds"].items()}
    elif "args" in item:
        sub["args"] = {f: np.asarray(a)[sel]
                       for f, a in item["args"].items()}
    return sub


VECTOR_STREAM_TARGET = "vector-stream-deliver"


class VectorStreamDeliverTarget:
    """Per-silo system target executing forwarded bulk stream items on
    the keys' owner silo (the remote half of single-owner delivery)."""

    def __init__(self, silo) -> None:
        self.silo = silo

    async def vector_stream_deliver(self, class_name: str, method: str,
                                    item: dict) -> int:
        vcls = self.silo.vector_interfaces.get(class_name)
        if vcls is None or self.silo.vector is None:
            raise LookupError(
                f"no vector interface {class_name!r} on this silo")
        return await _run_bulk_local_via(self.silo, self.silo.vector,
                                         vcls, method, item)


def install_vector_stream_target(silo) -> None:
    """Idempotently register the bulk-delivery system target (called when
    a persistent-stream provider is installed on a vector-hosting silo)."""
    if getattr(silo, "_vector_stream_target", None) is None and \
            silo.vector is not None:
        silo._vector_stream_target = VectorStreamDeliverTarget(silo)
        silo.register_system_target(silo._vector_stream_target,
                                    VECTOR_STREAM_TARGET)
