"""Stream pub-sub: rendezvous grain state + implicit subscriptions.

Re-design of /root/reference/src/Orleans.Runtime/Streams/PubSub/
PubSubRendezvousGrain.cs:21 (RegisterProducer :62, RegisterConsumer :115 —
durable per-stream subscriber sets held in grain state) and
src/Orleans.Core/Streams/PubSub/ImplicitStreamSubscriberTable.cs:11
(attribute-declared subscriptions resolved from the type map, no rendezvous
round-trip).
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from ..core.ids import GrainId, GrainType
from ..runtime.grain import StatefulGrain
from .core import StreamId, SubscriptionHandle

if TYPE_CHECKING:
    from ..runtime.silo import Silo

__all__ = ["PubSubRendezvousGrain", "implicit_stream_subscription",
           "implicit_consumers", "resolve_consumers", "deliver_to_consumer"]


class PubSubRendezvousGrain(StatefulGrain):
    """One per stream (key = str(StreamId)): the durable subscriber set."""

    async def register_consumer(self, handle: SubscriptionHandle) -> None:
        self.state.setdefault("consumers", {})[handle.handle_id] = handle
        await self.write_state()

    async def unregister_consumer(self, handle_id: str) -> None:
        if self.state.setdefault("consumers", {}).pop(handle_id, None):
            await self.write_state()

    async def get_consumers(self) -> list[SubscriptionHandle]:
        return list(self.state.get("consumers", {}).values())

    async def register_producer(self, producer: str) -> None:
        if producer not in self.state.setdefault("producers", []):
            self.state["producers"].append(producer)
            await self.write_state()

    async def counts(self) -> tuple[int, int]:
        return (len(self.state.get("producers", [])),
                len(self.state.get("consumers", {})))


def implicit_stream_subscription(namespace: str):
    """Class decorator: auto-subscribe every grain of this class to streams
    in ``namespace``, keyed by the stream key ([ImplicitStreamSubscription]).
    The grain must define ``async def on_next(self, item, token)``."""

    def deco(cls: type) -> type:
        existing = list(getattr(cls, "__implicit_stream_ns__", ()))
        cls.__implicit_stream_ns__ = (*existing, namespace)
        return cls

    return deco


def implicit_consumers(silo: "Silo", stream: StreamId) -> list[SubscriptionHandle]:
    """ImplicitStreamSubscriberTable: registered classes whose declared
    namespaces include this stream's — consumer key = stream key."""
    out = []
    for cls in silo.registry.all_classes():
        if stream.namespace in getattr(cls, "__implicit_stream_ns__", ()):
            gid = GrainId.for_grain(GrainType.of(cls.__name__), stream.key)
            out.append(SubscriptionHandle(
                stream=stream, handle_id=f"implicit:{cls.__name__}",
                grain_id=gid, interface_name=cls.__name__,
                method_name="on_next"))
    return out


def _rendezvous(silo: "Silo", stream: StreamId):
    return silo.grain_factory.get_grain(PubSubRendezvousGrain, str(stream))


async def resolve_consumers(silo: "Silo", stream: StreamId
                            ) -> list[SubscriptionHandle]:
    """Explicit (rendezvous state) + implicit (type map) subscribers."""
    explicit = await _rendezvous(silo, stream).get_consumers()
    return list(explicit) + implicit_consumers(silo, stream)


async def deliver_to_consumer(silo: "Silo", handle: SubscriptionHandle,
                              items: list, first_token: int) -> None:
    """Deliver events as ordinary grain calls (the consumer-extension path):
    ``await consumer.<method>(item, token)`` per event, in order."""
    cls = silo.registry.resolve(handle.interface_name)
    if cls is None:
        raise LookupError(
            f"stream consumer class {handle.interface_name} not registered")
    for i, item in enumerate(items):
        fut = silo.runtime_client.send_request(
            target_grain=handle.grain_id, grain_class=cls,
            interface_name=handle.interface_name,
            method_name=handle.method_name,
            args=(item, first_token + i), kwargs={})
        await fut
