"""Durable queue adapters: persistent-stream events that survive the process.

Re-design of the reference's externally-durable stream queues —
/root/reference/src/Azure/Orleans.Streaming.AzureStorage/Providers/Streams/
AzureQueue/AzureQueueAdapterReceiver.cs (+ ``AzureQueueAdapterFactory.cs``),
consumed by ``PersistentStreamPullingAgent.cs:350-368`` — with this repo's
standard durable-backend split (file / sqlite, the same split membership,
reminders, storage, the transaction log, and gossip channels use; cloud
queue services map onto these backends).

Durability contract:

* ``produce`` appends the batch durably BEFORE returning: an event accepted
  by ``on_next`` survives process death from that moment.
* Receivers deliver unacked batches. Acks are committed durably, so a
  restarted silo's pulling agent resumes from the durable cursor, and
  unacked batches redeliver (at-least-once; consumers dedup by token).
* Acked batches are RETAINED (bounded by ``retention`` per queue), so a
  rewound subscription (``subscribe(from_token=...)``) replays history
  beyond the in-memory cache window via :meth:`DurableQueueAdapter.replay`
  — the queue-retention replay the reference gets from EventHub offsets.

Blocking I/O (fsync, sqlite) runs in the default executor so produces and
acks never stall the silo's event loop.
"""

from __future__ import annotations

import asyncio
import base64
import contextlib
import json
import os
import sqlite3
import threading

from ..core.serialization import serialize_portable
from ..core.serialization import _restricted_pickle_loads as _loads
from .core import StreamId
from .persistent import QueueAdapter, QueueBatch, QueueReceiver

try:
    import fcntl
except ImportError:  # pragma: no cover — non-POSIX fallback
    fcntl = None

__all__ = ["DurableQueueAdapter", "FileQueueAdapter", "SqliteQueueAdapter"]


class _GroupCommitter:
    """Group commit: coalesce produces that are concurrently in flight
    into ONE durable commit (the batched-write analog of the reference's
    ``QueueMessageBatchAsync`` path consumed by
    PersistentStreamPullingAgent.cs:350-368). Entries arriving while a
    flush runs in the executor join the NEXT flush, so N concurrent
    producers share ~1 fsync per flush instead of paying one each; a solo
    producer flushes immediately — no batching-window latency is ever
    added. Each submitter's await completes only after the commit that
    contains its entry is durable (or fails, with the flush error)."""

    def __init__(self, flush):
        self._flush = flush  # flush(entries) — blocking, runs in executor
        self._pending: list = []
        self._task: asyncio.Task | None = None

    async def submit(self, entry) -> None:
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending.append((entry, fut))
        if self._task is None or self._task.done():
            self._task = loop.create_task(self._drain())
        await fut

    async def _drain(self) -> None:
        loop = asyncio.get_running_loop()
        while self._pending:
            batch, self._pending = self._pending, []
            entries = [e for e, _ in batch]
            try:
                await loop.run_in_executor(None, self._flush, entries)
            except asyncio.CancelledError:
                # loop teardown: the in-flight commit may still land in
                # the executor thread, but its waiters cannot learn that —
                # cancel them (at-least-once: a retry re-produces) and
                # STOP draining; swallowing the cancel would re-enter
                # run_in_executor on a closing loop
                for _, fut in batch:
                    if not fut.done():
                        fut.cancel()
                raise
            except BaseException as exc:  # noqa: BLE001 — to every waiter
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(exc)
            else:
                for _, fut in batch:
                    if not fut.done():
                        fut.set_result(None)


class DurableQueueAdapter(QueueAdapter):
    """Shared contract of the durable backends; adds :meth:`replay` (the
    rewind-beyond-cache source consumed by the pulling agent's pumps) and
    the group-commit produce path."""

    def _flush_entries(self, entries: list) -> None:
        """Blocking: durably commit a produce flush group (subclass hook)."""
        raise NotImplementedError

    def _flush_acks(self, entries: list) -> None:
        """Blocking: durably commit an ack flush group (subclass hook)."""
        raise NotImplementedError

    def _committer(self, kind: str, flush) -> _GroupCommitter:
        """One committer per (event loop, kind): adapters are shared
        objects (the 'external queue service'), and tests drive them from
        several sequential loops — futures must never cross loops.
        Committers of closed loops are pruned so sequential loops (and
        their retained tasks/futures) do not accumulate."""
        by_key = getattr(self, "_committers", None)
        if by_key is None:
            by_key = self._committers = {}
        for stale in [k for k in by_key if k[0].is_closed()]:
            del by_key[stale]
        key = (asyncio.get_running_loop(), kind)
        c = by_key.get(key)
        if c is None:
            c = by_key[key] = _GroupCommitter(flush)
        return c

    async def replay(self, stream: StreamId,
                     from_seq: int) -> list[QueueBatch]:
        """Acked batches of ``stream`` whose token range reaches
        ``from_seq`` or later, in order. Only ACKED batches: unacked ones
        redeliver through the normal pull path, so replaying them here
        would double-deliver the live window."""
        raise NotImplementedError

    def queue_of(self, stream: StreamId) -> int:
        return stream.uniform_hash % self.n_queues


class _DurableReceiver(QueueReceiver):
    """Receiver over a durable backend: the backend knows acked state; this
    object only tracks what THIS incarnation already handed out, so a fresh
    receiver (silo restart / queue-ownership handoff) redelivers every
    unacked batch — the durable-cursor resume."""

    def __init__(self, adapter, queue_id: int):
        self._adapter = adapter
        self._queue_id = queue_id
        self._delivered: set[int] = set()

    async def get_messages(self, max_count: int) -> list[QueueBatch]:
        batches = await self._adapter._unacked(
            self._queue_id, self._delivered, max_count)
        self._delivered.update(b.seq for b in batches)
        return batches

    async def ack(self, batch: QueueBatch) -> None:
        await self._adapter._ack(self._queue_id, batch.seq)
        self._delivered.discard(batch.seq)

    def shutdown(self) -> None:
        # acks are durable; dropping the delivered set is all a handoff
        # needs — the next owner's receiver re-reads unacked rows
        self._delivered.clear()


class SqliteQueueAdapter(DurableQueueAdapter):
    """Sqlite-backed queue bank (the AdoNet/AzureQueue analog): one
    database file is the cluster-shared queue service. WAL mode; one
    connection guarded by a lock, used from the executor."""

    def __init__(self, path: str, n_queues: int = 8, name: str = "sqlite",
                 retention: int = 4096):
        self.name = name
        self.n_queues = n_queues
        self.retention = retention
        self.path = path
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._db.execute("PRAGMA journal_mode=WAL")
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS stream_batches ("
                " queue_id INTEGER, seq INTEGER, stream BLOB, items BLOB,"
                " n INTEGER, acked INTEGER DEFAULT 0,"
                " PRIMARY KEY (queue_id, seq))")
            # per-queue high-water mark (the sqlite analog of the file
            # adapter's watermark record): retention can DELETE every row
            # of a drained queue, and deriving next-seq from surviving rows
            # alone would then restart at 0 and collide with
            # already-delivered tokens
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS stream_watermarks ("
                " queue_id INTEGER PRIMARY KEY, next_seq INTEGER)")
            self._db.commit()

    def close(self) -> None:
        with self._lock:
            self._db.close()

    async def queue_message_batch(self, queue_id, stream, items) -> None:
        blob = serialize_portable(list(items))
        sblob = serialize_portable(stream)
        await self._committer("produce", self._flush_entries).submit(
            (queue_id, sblob, blob, len(items)))

    def _flush_entries(self, entries: list) -> None:
        """One transaction (one WAL fsync) commits every produce in the
        flush group."""
        with self._lock:
            # BEGIN IMMEDIATE takes the write lock BEFORE the seq
            # read: two producer PROCESSES sharing this .db must not
            # both read the same max seq (deferred transactions would
            # let them, and one INSERT would die on the PK)
            self._db.execute("BEGIN IMMEDIATE")
            try:
                next_seq: dict[int, int] = {}
                for queue_id, sblob, blob, n in entries:
                    if queue_id not in next_seq:
                        # item-cumulative per-queue seq
                        # (EventSequenceToken contract): next = previous
                        # seq + previous item count. max() with the
                        # watermark: rows alone under-count after
                        # retention drained the queue; the watermark
                        # alone under-counts on a pre-watermark db
                        row = self._db.execute(
                            "SELECT seq + n FROM stream_batches"
                            " WHERE queue_id=?"
                            " ORDER BY seq DESC LIMIT 1",
                            (queue_id,)).fetchone()
                        wm = self._db.execute(
                            "SELECT next_seq FROM stream_watermarks"
                            " WHERE queue_id=?", (queue_id,)).fetchone()
                        next_seq[queue_id] = max(row[0] if row else 0,
                                                 wm[0] if wm else 0)
                    seq = next_seq[queue_id]
                    self._db.execute(
                        "INSERT INTO stream_batches"
                        " (queue_id, seq, stream, items, n)"
                        " VALUES (?,?,?,?,?)",
                        (queue_id, seq, sblob, blob, n))
                    next_seq[queue_id] = seq + n
                for queue_id, ns in next_seq.items():
                    self._db.execute(
                        "INSERT OR REPLACE INTO stream_watermarks"
                        " (queue_id, next_seq) VALUES (?,?)",
                        (queue_id, ns))
                self._db.commit()
            except BaseException:
                self._db.rollback()
                raise

    def create_receiver(self, queue_id: int) -> QueueReceiver:
        return _DurableReceiver(self, queue_id)

    async def _unacked(self, queue_id: int, exclude: set[int],
                       max_count: int) -> list[QueueBatch]:
        # bound the fetch: at most max_count new rows can be returned, so
        # max_count + |delivered-but-unacked| rows suffice — a large
        # backlog under consumer backpressure must not make every poll
        # scan the whole queue
        limit = max_count + len(exclude)

        def read():
            with self._lock:
                return self._db.execute(
                    "SELECT seq, stream, items FROM stream_batches"
                    " WHERE queue_id=? AND acked=0 ORDER BY seq LIMIT ?",
                    (queue_id, limit)).fetchall()

        rows = await asyncio.get_running_loop().run_in_executor(None, read)
        out = []
        for seq, sblob, blob in rows:
            if seq in exclude:
                continue
            out.append(QueueBatch(_loads(sblob), _loads(blob), seq))
            if len(out) >= max_count:
                break
        return out

    async def _ack(self, queue_id: int, seq: int) -> None:
        await self._committer("ack", self._flush_acks).submit(
            (queue_id, seq))

    def _flush_acks(self, entries: list) -> None:
        """One transaction acks the whole flush group; the retention
        sweep runs once per touched queue, not once per ack."""
        with self._lock:
            self._db.execute("BEGIN IMMEDIATE")
            try:
                self._db.executemany(
                    "UPDATE stream_batches SET acked=1"
                    " WHERE queue_id=? AND seq=?", entries)
                for queue_id in {q for q, _ in entries}:
                    # bounded retention: keep the newest `retention` acked
                    # batches per queue for rewind replay, drop older
                    self._db.execute(
                        "DELETE FROM stream_batches WHERE queue_id=?"
                        " AND acked=1"
                        " AND seq NOT IN (SELECT seq FROM stream_batches"
                        "  WHERE queue_id=? AND acked=1"
                        "  ORDER BY seq DESC LIMIT ?)",
                        (queue_id, queue_id, self.retention))
                self._db.commit()
            except BaseException:
                self._db.rollback()
                raise

    async def replay(self, stream: StreamId,
                     from_seq: int) -> list[QueueBatch]:
        queue_id = self.queue_of(stream)

        def read():
            with self._lock:
                return self._db.execute(
                    "SELECT seq, stream, items FROM stream_batches"
                    " WHERE queue_id=? AND acked=1 AND seq + n > ?"
                    " ORDER BY seq", (queue_id, from_seq)).fetchall()

        rows = await asyncio.get_running_loop().run_in_executor(None, read)
        return [QueueBatch(s, _loads(blob), seq)
                for seq, sblob, blob in rows
                if (s := _loads(sblob)) == stream]


class FileQueueAdapter(DurableQueueAdapter):
    """Append-only file-backed queue bank: one directory is the queue
    service. Per queue: ``q<i>.log`` (one JSON line per batch, payload
    pickled+base64) and ``q<i>.ack`` (one acked seq per line). fsync per
    produce — the durability point. A torn trailing line (crash mid-write)
    is detected on parse and ignored; the producer that crashed never had
    its produce() return, so nothing acknowledged is lost."""

    def __init__(self, directory: str, n_queues: int = 8,
                 name: str = "file", retention: int = 4096):
        self.name = name
        self.n_queues = n_queues
        # newest acked batches kept per queue for rewind replay; older
        # acked batches are dropped by compaction (a log rewrite with a
        # seq-watermark record so token continuity survives), triggered
        # once enough acks accumulate — the log is bounded, not
        # append-forever
        self.retention = retention
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._next_seq: dict[int, int] = {}
        self._scanned: dict[int, int] = {}  # queue -> file size at scan
        self._acks_since_compact: dict[int, int] = {}

    def _log(self, q: int) -> str:
        return os.path.join(self.directory, f"q{q}.log")

    def _ackf(self, q: int) -> str:
        return os.path.join(self.directory, f"q{q}.ack")

    @contextlib.contextmanager
    def _os_lock(self, q: int):
        """Cross-process exclusive lock per queue (flock on a sidecar):
        seq assignment must be atomic between producer PROCESSES."""
        if fcntl is None:  # pragma: no cover
            yield
            return
        with open(self._log(q) + ".lock", "a+") as lk:
            fcntl.flock(lk.fileno(), fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(lk.fileno(), fcntl.LOCK_UN)

    def _read_log_raw(self, q: int) -> tuple[
            list[tuple[int, bytes, bytes, int]], int, int]:
        """Parse q<i>.log into ``(rows, valid_end, next_seq)``:
        ``rows`` are (seq, stream_blob, items_blob, n_items) batch
        records; ``valid_end`` is the byte length of the VALID prefix;
        ``next_seq`` is the next token to assign — the max over every
        record (including compaction watermarks ``{"s":…, "w":1}``,
        which carry the sequence over dropped history) of seq + n.

        A torn trailing line (crash mid-append: unterminated or
        unparseable) ends the valid prefix — that writer's produce()
        never returned, so the torn record was never acknowledged to
        anyone. The producer truncates the torn tail before appending:
        appending after it would leave the new record unreachable behind
        the parse stop."""
        path = self._log(q)
        if not os.path.exists(path):
            return [], 0, 0
        rows: list = []
        valid_end = 0
        next_seq = 0
        with open(path, "rb") as f:
            for line in f:
                if not line.endswith(b"\n"):
                    break  # torn tail from a crashed writer
                stripped = line.strip()
                if stripped:
                    try:
                        r = json.loads(stripped)
                        if r.get("w"):
                            # compaction watermark: preserves the token
                            # sequence across dropped history
                            next_seq = max(next_seq, r["s"])
                        else:
                            rows.append((r["s"],
                                         base64.b64decode(r["sid"]),
                                         base64.b64decode(r["b"]), r["n"]))
                            next_seq = max(next_seq, r["s"] + r["n"])
                    except (ValueError, KeyError):
                        break
                valid_end += len(line)
        return rows, valid_end, next_seq

    def _read_log(self, q: int) -> list[tuple[int, bytes, bytes, int]]:
        return self._read_log_raw(q)[0]

    def _read_acks(self, q: int) -> set[int]:
        path = self._ackf(q)
        if not os.path.exists(path):
            return set()
        acked = set()
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        acked.add(int(line))
                    except ValueError:
                        break
        return acked

    async def queue_message_batch(self, queue_id, stream, items) -> None:
        rec = {"sid": base64.b64encode(
                   serialize_portable(stream)).decode(),
               "b": base64.b64encode(
                   serialize_portable(list(items))).decode(),
               "n": len(items)}
        await self._committer("produce", self._flush_entries).submit(
            (queue_id, rec))

    def _flush_entries(self, entries: list) -> None:
        """One append + one fsync per QUEUE per flush group commits every
        produce in the group."""
        by_q: dict[int, list[dict]] = {}
        for queue_id, rec in entries:
            by_q.setdefault(queue_id, []).append(rec)
        with self._lock:
            for queue_id, recs in by_q.items():
                with self._os_lock(queue_id):
                    # cached next-seq, revalidated by file size under the
                    # flock: steady-state single-process produce is O(1);
                    # a cross-process writer (or a torn tail) shows up as
                    # a size mismatch and forces one rescan (the
                    # FileTransactionLog index pattern)
                    path = self._log(queue_id)
                    try:
                        size = os.path.getsize(path)
                    except OSError:
                        size = 0
                    if self._scanned.get(queue_id) != size:
                        _rows, valid_end, next_seq = \
                            self._read_log_raw(queue_id)
                        if valid_end < size:
                            # truncate a crashed writer's torn tail so
                            # the records appended below stay parseable
                            with open(path, "r+b") as tf:
                                tf.truncate(valid_end)
                        self._next_seq[queue_id] = next_seq
                    seq = self._next_seq.get(queue_id, 0)
                    with open(path, "a", encoding="utf-8") as f:
                        for rec in recs:
                            rec["s"] = seq
                            seq += rec["n"]
                            f.write(json.dumps(rec, separators=(",", ":"))
                                    + "\n")
                        f.flush()
                        os.fsync(f.fileno())
                        self._scanned[queue_id] = f.tell()
                    self._next_seq[queue_id] = seq

    def create_receiver(self, queue_id: int) -> QueueReceiver:
        return _DurableReceiver(self, queue_id)

    async def _unacked(self, queue_id: int, exclude: set[int],
                       max_count: int) -> list[QueueBatch]:
        def read():
            with self._lock:
                rows = self._read_log(queue_id)
                acked = self._read_acks(queue_id)
            out = []
            for seq, sblob, blob, _n in rows:
                if seq in acked or seq in exclude:
                    continue
                out.append(QueueBatch(_loads(sblob), _loads(blob), seq))
                if len(out) >= max_count:
                    break
            return out

        return await asyncio.get_running_loop().run_in_executor(None, read)

    async def _ack(self, queue_id: int, seq: int) -> None:
        await self._committer("ack", self._flush_acks).submit(
            (queue_id, seq))

    def _flush_acks(self, entries: list) -> None:
        """One append + one fsync per queue acks the whole flush group;
        the compaction check runs once per touched queue."""
        by_q: dict[int, list[int]] = {}
        for queue_id, seq in entries:
            by_q.setdefault(queue_id, []).append(seq)
        with self._lock:
            for queue_id, seqs in by_q.items():
                # the flock serializes against a concurrent compaction in
                # ANOTHER process: its ack-file rewrite must never discard
                # an ack appended between its read and its replace
                with self._os_lock(queue_id):
                    with open(self._ackf(queue_id), "a",
                              encoding="utf-8") as f:
                        f.writelines(f"{seq}\n" for seq in seqs)
                        f.flush()
                        os.fsync(f.fileno())
                    n = self._acks_since_compact.get(queue_id, 0) \
                        + len(seqs)
                    if n >= max(self.retention, 64):
                        self._compact_under_flock(queue_id)
                        n = 0
                    self._acks_since_compact[queue_id] = n

    def _compact_locked(self, q: int) -> None:
        """Compact with only ``_lock`` held (takes the flock itself).
        Never call while already holding the flock — flock on a second
        fd of the same lock file blocks even within one process."""
        with self._os_lock(q):
            self._compact_under_flock(q)

    def _compact_under_flock(self, q: int) -> None:
        """Bound the log: keep unacked batches plus the newest
        ``retention`` acked ones; a leading watermark record carries the
        token sequence over the dropped history. Caller holds ``_lock``
        AND the queue flock (which serializes against cross-process
        producers and ackers). Replace order is log-then-ack: a crash
        between the two leaves stale seqs in the ack file (harmless —
        acks for absent batches are ignored) but never un-acks a kept
        batch."""
        rows, _, next_seq = self._read_log_raw(q)
        acked = self._read_acks(q)
        acked_seqs = sorted(r[0] for r in rows if r[0] in acked)
        # [-0:] would keep EVERYTHING; retention=0 means no history
        keep_acked = set(acked_seqs[-self.retention:]) \
            if self.retention > 0 else set()
        kept = [r for r in rows
                if r[0] not in acked or r[0] in keep_acked]
        path = self._log(q)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(json.dumps({"s": next_seq, "w": 1},
                               separators=(",", ":")) + "\n")
            for seq, sblob, blob, n in kept:
                f.write(json.dumps(
                    {"s": seq,
                     "sid": base64.b64encode(sblob).decode(),
                     "b": base64.b64encode(blob).decode(),
                     "n": n}, separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        ackf = self._ackf(q)
        atmp = ackf + ".tmp"
        with open(atmp, "w", encoding="utf-8") as f:
            for seq in sorted(keep_acked):
                f.write(f"{seq}\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(atmp, ackf)
        self._scanned[q] = os.path.getsize(path)
        self._next_seq[q] = next_seq

    async def replay(self, stream: StreamId,
                     from_seq: int) -> list[QueueBatch]:
        queue_id = self.queue_of(stream)

        def read():
            with self._lock:
                rows = self._read_log(queue_id)
                acked = self._read_acks(queue_id)
            out = []
            for seq, sblob, blob, n in rows:
                if seq not in acked or seq + n <= from_seq:
                    continue
                sid = _loads(sblob)
                if sid == stream:
                    out.append(QueueBatch(sid, _loads(blob), seq))
            return out

        return await asyncio.get_running_loop().run_in_executor(None, read)
