"""Simple Message Streams: direct producer→consumer fan-out, no queue.

Re-design of /root/reference/src/Orleans.Core/Streams/SimpleMessageStream/
SimpleMessageStreamProducer.cs:12 + SimpleMessageStreamProducerExtension.cs:
each event is fanned out as grain calls to every subscribed consumer at
publish time; optional fire-and-forget delivery.
"""

from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING

from ..core.errors import StreamError
from .core import StreamId, StreamProvider, SubscriptionHandle
from .pubsub import (
    PubSubRendezvousGrain,
    deliver_to_consumer,
    resolve_consumers,
)

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.streams.sms")

__all__ = ["SMSStreamProvider", "add_sms_streams"]


class SMSStreamProvider(StreamProvider):
    """Direct fan-out provider ("SMS")."""

    def __init__(self, silo: "Silo", name: str,
                 fire_and_forget: bool = False):
        super().__init__(silo, name)
        self.fire_and_forget = fire_and_forget
        self._seq = 0

    async def produce(self, stream: StreamId, items: list) -> None:
        consumers = await resolve_consumers(self.silo, stream)
        # item-cumulative: per-item tokens (token + i) stay unique across
        # batches (consumers dedup by token — see deliver_to_consumer)
        token = self._seq
        self._seq += len(items)
        self.silo.stats.increment("streams.sms.produced", len(items))
        deliveries = [
            deliver_to_consumer(self.silo, h, items, token)
            for h in consumers
        ]
        if self.fire_and_forget:
            for d in deliveries:
                asyncio.ensure_future(_swallow(d))
        else:
            results = await asyncio.gather(*deliveries,
                                           return_exceptions=True)
            errors = [r for r in results if isinstance(r, BaseException)]
            if errors:
                raise errors[0]

    async def register_consumer(self, handle: SubscriptionHandle) -> None:
        if getattr(handle, "from_token", None) is not None:
            raise StreamError(
                "SMS streams are not rewindable (no cache to replay "
                "from) — use a persistent (queue-backed) provider for "
                "from_token subscriptions")
        await self._rendezvous(handle.stream).register_consumer(handle)

    async def unregister_consumer(self, handle: SubscriptionHandle) -> None:
        await self._rendezvous(handle.stream).unregister_consumer(
            handle.handle_id)

    async def consumer_handles(self, stream: StreamId):
        return await resolve_consumers(self.silo, stream)

    def _rendezvous(self, stream: StreamId):
        return self.silo.grain_factory.get_grain(
            PubSubRendezvousGrain, str(stream))


async def _swallow(coro) -> None:
    try:
        await coro
    except Exception:  # noqa: BLE001 — fire-and-forget drops errors, logged
        log.debug("fire-and-forget stream delivery failed", exc_info=True)


def add_sms_streams(builder, name: str = "sms",
                    fire_and_forget: bool = False):
    """Register the SMS provider + pubsub grain on a SiloBuilder."""
    builder.add_grains(PubSubRendezvousGrain)

    def install(silo) -> None:
        silo.stream_providers[name] = SMSStreamProvider(
            silo, name, fire_and_forget)

    return builder.configure(install)
