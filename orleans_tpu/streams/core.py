"""Stream model: identities, handles, and the provider contract.

Re-design of /root/reference/src/Orleans.Core/Streams/:
``StreamImpl`` (Internal/StreamImpl.cs:13 — Subscribe :60, OnNext :89),
``StreamId``/``IAsyncStream<T>`` (virtual streams addressed by guid+namespace),
``StreamSubscriptionHandle``. Providers implement ``get_stream`` and the
producer/consumer plumbing; consumers are grains — a subscription records
(grain id, method) and delivery is an ordinary grain call, the analog of the
``StreamConsumerExtension`` piggybacking on grain messaging.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, Callable

from ..core.errors import StreamError
from ..core.ids import GrainId, stable_hash64

if TYPE_CHECKING:
    from ..runtime.silo import Silo

__all__ = ["StreamId", "StreamRef", "SubscriptionHandle", "StreamProvider"]


@dataclass(frozen=True)
class StreamId:
    """Stream identity = (provider, namespace, key) — StreamId.cs."""

    provider: str
    namespace: str
    key: str

    @property
    def uniform_hash(self) -> int:
        return stable_hash64(f"stream|{self.provider}|{self.namespace}|{self.key}")

    def __str__(self) -> str:
        return f"{self.provider}/{self.namespace}/{self.key}"


@dataclass(frozen=True)
class SubscriptionHandle:
    """Opaque subscription token (StreamSubscriptionHandle<T>)."""

    stream: StreamId
    handle_id: str
    grain_id: GrainId
    interface_name: str
    method_name: str
    # batch consumer (IAsyncBatchObserver<T>): deliveries arrive as ONE
    # call per queue batch — method(items, first_token) — instead of a
    # grain call per event
    batch: bool = False
    # rewound subscription (StreamSequenceToken resume): deliver only
    # events with token >= from_token, replaying older ones from the
    # pulling agent's cache where still present (events already purged
    # are clamped to the oldest cached — the reference's cache-window
    # replay contract). None = from now/oldest-cached as usual.
    from_token: int | None = None


def consumer_of(handler: Callable) -> tuple[GrainId, str, str]:
    """Extract (grain id, interface, method) from a bound grain method —
    the subscription record. The handler must be ``self.method`` of a live
    grain so delivery can route as a grain call after re-activation."""
    owner = getattr(handler, "__self__", None)
    if owner is None or not hasattr(owner, "grain_id"):
        raise StreamError(
            "stream handlers must be bound methods of a grain "
            "(e.g. stream.subscribe(self.on_event))")
    return owner.grain_id, type(owner).__name__, handler.__name__


def batch_consumer(fn: Callable) -> Callable:
    """Mark a stream handler as a BATCH consumer (the
    ``IAsyncBatchObserver<T>`` role): it receives
    ``(items: list, first_token: int)`` once per delivered batch instead
    of one grain call per event. Subscribing such a method picks batch
    delivery automatically; redelivery after a failure re-sends the
    not-yet-acknowledged remainder of the batch (at-least-once, dedup by
    token as usual)."""
    fn.__orleans_stream_batch__ = True
    return fn


class StreamRef:
    """The user-facing stream handle (IAsyncStream<T>): produce + subscribe.
    Cheap to create; all state lives in pubsub/queues."""

    def __init__(self, provider: "StreamProvider", stream: StreamId):
        self.provider = provider
        self.stream_id = stream

    # -- producer side (StreamImpl.OnNext :89) --------------------------
    async def on_next(self, item: Any) -> None:
        await self.provider.produce(self.stream_id, [item])

    async def on_next_batch(self, items: list) -> None:
        await self.provider.produce(self.stream_id, list(items))

    async def on_completed(self) -> None:
        await self.provider.complete(self.stream_id)

    # -- consumer side (StreamImpl.Subscribe :60) -----------------------
    async def subscribe(self, handler: Callable,
                        batch: bool | None = None,
                        from_token: int | None = None) -> SubscriptionHandle:
        """Subscribe a bound grain method. ``batch`` (or the
        ``@batch_consumer`` marker) selects whole-batch delivery;
        ``from_token`` resumes a rewindable (persistent) stream from a
        sequence token, replaying from the provider's cache window."""
        grain_id, iface, method = consumer_of(handler)
        if batch is None:
            batch = bool(getattr(handler, "__orleans_stream_batch__", False))
        handle = SubscriptionHandle(
            stream=self.stream_id, handle_id=uuid.uuid4().hex,
            grain_id=grain_id, interface_name=iface, method_name=method,
            batch=batch, from_token=from_token)
        await self.provider.register_consumer(handle)
        return handle

    async def unsubscribe(self, handle: SubscriptionHandle) -> None:
        await self.provider.unregister_consumer(handle)

    async def subscription_handles(self) -> list[SubscriptionHandle]:
        return await self.provider.consumer_handles(self.stream_id)


class StreamProvider:
    """Provider contract (IStreamProvider). Subclasses: SMS (direct fan-out)
    and persistent (queue-backed)."""

    def __init__(self, silo: "Silo", name: str):
        self.silo = silo
        self.name = name

    def get_stream(self, namespace: str, key) -> StreamRef:
        return StreamRef(self, StreamId(self.name, namespace, str(key)))

    # -- to implement ----------------------------------------------------
    async def produce(self, stream: StreamId, items: list) -> None:
        raise NotImplementedError

    async def complete(self, stream: StreamId) -> None:  # noqa: B027
        pass

    async def register_consumer(self, handle: SubscriptionHandle) -> None:
        raise NotImplementedError

    async def unregister_consumer(self, handle: SubscriptionHandle) -> None:
        raise NotImplementedError

    async def consumer_handles(self, stream: StreamId) -> list[SubscriptionHandle]:
        raise NotImplementedError
