"""Stream model: identities, handles, and the provider contract.

Re-design of /root/reference/src/Orleans.Core/Streams/:
``StreamImpl`` (Internal/StreamImpl.cs:13 — Subscribe :60, OnNext :89),
``StreamId``/``IAsyncStream<T>`` (virtual streams addressed by guid+namespace),
``StreamSubscriptionHandle``. Providers implement ``get_stream`` and the
producer/consumer plumbing; consumers are grains — a subscription records
(grain id, method) and delivery is an ordinary grain call, the analog of the
``StreamConsumerExtension`` piggybacking on grain messaging.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, Callable

from ..core.errors import StreamError
from ..core.ids import GrainId, stable_hash64

if TYPE_CHECKING:
    from ..runtime.silo import Silo

__all__ = ["StreamId", "StreamRef", "StreamSignal", "SubscriptionHandle",
           "StreamProvider"]


@dataclass(frozen=True)
class StreamId:
    """Stream identity = (provider, namespace, key) — StreamId.cs."""

    provider: str
    namespace: str
    key: str

    @property
    def uniform_hash(self) -> int:
        return stable_hash64(f"stream|{self.provider}|{self.namespace}|{self.key}")

    def __str__(self) -> str:
        return f"{self.provider}/{self.namespace}/{self.key}"


@dataclass(frozen=True)
class StreamSignal:
    """Producer-signaled control event riding the normal item path as a
    single-item batch: ``kind`` is ``"error"`` (OnErrorAsync —
    AsyncObservableExtensions.cs:19-41 routes it to the observer's
    onErrorAsync delegate) or ``"completed"`` (OnCompletedAsync). Signals
    consume one sequence token like any item, so ordering relative to
    data, durable replay, and token dedup all hold unchanged."""

    kind: str
    error: Any = None


@dataclass(frozen=True)
class SubscriptionHandle:
    """Opaque subscription token (StreamSubscriptionHandle<T>)."""

    stream: StreamId
    handle_id: str
    grain_id: GrainId
    interface_name: str
    method_name: str
    # consumer-side OnError/OnCompleted methods (GenericAsyncObserver.cs:37
    # holds the three delegates; here: method names on the SAME grain).
    # None = the consumer declined that part of the contract; the signal
    # is then logged and dropped, as the reference does for null delegates
    error_method_name: str | None = None
    completed_method_name: str | None = None
    # batch consumer (IAsyncBatchObserver<T>): deliveries arrive as ONE
    # call per queue batch — method(items, first_token) — instead of a
    # grain call per event
    batch: bool = False
    # rewound subscription (StreamSequenceToken resume): deliver only
    # events with token >= from_token, replaying older ones from the
    # pulling agent's cache where still present (events already purged
    # are clamped to the oldest cached — the reference's cache-window
    # replay contract). None = from now/oldest-cached as usual.
    from_token: int | None = None
    # span-link arming context: the (trace_id, span_id) of the turn that
    # SUBSCRIBED, when sampled. Stream deliveries from pulling agents
    # root fresh traces; the new roots carry this as a span link so
    # Perfetto/OTLP show which subscription armed the work
    # (observability.tracing.pending_root_link). None for implicit
    # subscribers and untraced subscribes.
    link: tuple | None = None


def consumer_of(handler: Callable) -> tuple[GrainId, str, str]:
    """Extract (grain id, interface, method) from a bound grain method —
    the subscription record. The handler must be ``self.method`` of a live
    grain so delivery can route as a grain call after re-activation."""
    owner = getattr(handler, "__self__", None)
    if owner is None or not hasattr(owner, "grain_id"):
        raise StreamError(
            "stream handlers must be bound methods of a grain "
            "(e.g. stream.subscribe(self.on_event))")
    return owner.grain_id, type(owner).__name__, handler.__name__


def batch_consumer(fn: Callable) -> Callable:
    """Mark a stream handler as a BATCH consumer (the
    ``IAsyncBatchObserver<T>`` role): it receives
    ``(items: list, first_token: int)`` once per delivered batch instead
    of one grain call per event. Subscribing such a method picks batch
    delivery automatically; redelivery after a failure re-sends the
    not-yet-acknowledged remainder of the batch (at-least-once, dedup by
    token as usual)."""
    fn.__orleans_stream_batch__ = True
    return fn


class StreamRef:
    """The user-facing stream handle (IAsyncStream<T>): produce + subscribe.
    Cheap to create; all state lives in pubsub/queues."""

    def __init__(self, provider: "StreamProvider", stream: StreamId):
        self.provider = provider
        self.stream_id = stream

    # -- producer side (StreamImpl.OnNext :89) --------------------------
    async def on_next(self, item: Any) -> None:
        if isinstance(item, StreamSignal):
            raise StreamError("StreamSignal is not a data item; use "
                              "on_error()/on_completed()")
        await self.provider.produce(self.stream_id, [item])

    async def on_next_batch(self, items: list) -> None:
        items = list(items)
        if any(isinstance(i, StreamSignal) for i in items):
            raise StreamError("StreamSignal is not a data item; use "
                              "on_error()/on_completed()")
        await self.provider.produce(self.stream_id, items)

    async def on_error(self, exc: BaseException) -> None:
        """Producer signals failure to every subscriber (OnErrorAsync).
        Rides the normal produce path as its own single-item batch, so
        it is ordered after everything already produced and — on a
        durable provider — survives and replays like data."""
        await self.provider.produce(
            self.stream_id, [StreamSignal(kind="error", error=exc)])

    async def on_completed(self) -> None:
        await self.provider.complete(self.stream_id)

    # -- consumer side (StreamImpl.Subscribe :60) -----------------------
    async def subscribe(self, handler: Callable,
                        batch: bool | None = None,
                        from_token: int | None = None,
                        on_error: Callable | None = None,
                        on_completed: Callable | None = None,
                        ) -> SubscriptionHandle:
        """Subscribe a bound grain method. ``batch`` (or the
        ``@batch_consumer`` marker) selects whole-batch delivery;
        ``from_token`` resumes a rewindable (persistent) stream from a
        sequence token, replaying from the provider's cache window.
        ``on_error`` / ``on_completed`` are further bound methods of the
        SAME grain receiving producer signals: ``on_error(exc, token)``
        and ``on_completed(token)`` — the observer triple of
        GenericAsyncObserver.cs:37."""
        grain_id, iface, method = consumer_of(handler)
        err_method = comp_method = None
        if on_error is not None:
            egid, _, err_method = consumer_of(on_error)
            if egid != grain_id:
                raise StreamError("on_error must be a method of the same "
                                  "grain as the data handler")
        if on_completed is not None:
            cgid, _, comp_method = consumer_of(on_completed)
            if cgid != grain_id:
                raise StreamError("on_completed must be a method of the "
                                  "same grain as the data handler")
        if batch is None:
            batch = bool(getattr(handler, "__orleans_stream_batch__", False))
        from ..observability.tracing import current_trace
        handle = SubscriptionHandle(
            stream=self.stream_id, handle_id=uuid.uuid4().hex,
            grain_id=grain_id, interface_name=iface, method_name=method,
            batch=batch, from_token=from_token,
            error_method_name=err_method, completed_method_name=comp_method,
            link=current_trace.get())
        await self.provider.register_consumer(handle)
        return handle

    async def unsubscribe(self, handle: SubscriptionHandle) -> None:
        await self.provider.unregister_consumer(handle)

    async def subscription_handles(self) -> list[SubscriptionHandle]:
        return await self.provider.consumer_handles(self.stream_id)


class StreamProvider:
    """Provider contract (IStreamProvider). Subclasses: SMS (direct fan-out)
    and persistent (queue-backed)."""

    def __init__(self, silo: "Silo", name: str):
        self.silo = silo
        self.name = name

    def get_stream(self, namespace: str, key) -> StreamRef:
        return StreamRef(self, StreamId(self.name, namespace, str(key)))

    # -- to implement ----------------------------------------------------
    async def produce(self, stream: StreamId, items: list) -> None:
        raise NotImplementedError

    async def complete(self, stream: StreamId) -> None:
        """Completion is a signal through the same ordered path as data
        (subscribers with a ``completed_method_name`` hear it; others
        ignore it)."""
        try:
            await self.produce(stream, [StreamSignal(kind="completed")])
        except StreamError as e:
            # a produce-rejecting adapter (e.g. the generator provider)
            # cannot carry signals either — name the actual operation
            raise StreamError(
                f"on_completed not supported on {stream}: {e}") from e

    async def register_consumer(self, handle: SubscriptionHandle) -> None:
        raise NotImplementedError

    async def unregister_consumer(self, handle: SubscriptionHandle) -> None:
        raise NotImplementedError

    async def consumer_handles(self, stream: StreamId) -> list[SubscriptionHandle]:
        raise NotImplementedError
