"""Core identity, message, and serialization layers (reference L0/L1)."""

from .asyncs import (  # noqa: F401
    AsyncPipeline,
    AsyncSerialExecutor,
    BatchWorker,
    ExponentialBackoff,
    retry,
)
from .errors import *  # noqa: F401,F403
from .ids import (  # noqa: F401
    ActivationAddress,
    ActivationId,
    GrainCategory,
    GrainId,
    GrainType,
    SiloAddress,
    stable_hash32,
    stable_hash64,
    type_code_of,
)
from .message import (  # noqa: F401
    Category,
    Direction,
    Message,
    RejectionType,
    ResponseKind,
    make_request,
    make_response,
    make_error_response,
    make_rejection,
)
from .serialization import (  # noqa: F401
    ArrayField,
    ArraySchema,
    Immutable,
    allow_wire_modules,
    deep_copy,
    deserialize,
    register_copier,
    serialize,
)
