"""Serialization & payload schemas (L1).

The reference has a 9,369-LoC three-tier serializer stack (codegen'd → IL-emitted
→ fallback; /root/reference/src/Orleans.Core/Serialization/SerializationManager.cs:50,133)
because every message crosses a socket. The TPU build's tiers are different:

1. **Device tier** — payloads for vectorized grains are *array schemas*: fixed
   dtype/shape pytrees that pack directly into batched kernel operands. This is
   the analog of codegen'd serializers: zero-copy into the dispatch tick.
2. **Host tier** — in-process messages are passed by reference; Orleans instead
   deep-copies arguments for isolation (``SerializationManager.DeepCopy``,
   registration :173-201). We keep that semantic behind :func:`deep_copy`
   honoring an ``Immutable`` wrapper (``Concurrency/Immutable.cs``).
3. **Wire tier** — cross-process control-plane bytes use a self-describing
   pickle-based codec with a type allowlist hook (the fallback-serializer slot).
"""

from __future__ import annotations

import copy
import io
import pickle
from dataclasses import dataclass
from typing import Any, Callable

import numpy as np

__all__ = [
    "Immutable", "deep_copy", "serialize", "deserialize",
    "allow_wire_modules", "ArrayField", "ArraySchema", "register_copier",
    "register_wire_codec", "unregister_wire_codec",
]


@dataclass(frozen=True)
class Immutable:
    """Marker wrapper: the sender promises not to mutate ``value`` so the
    runtime may skip deep-copy isolation (``Immutable<T>``)."""

    value: Any


_copiers: dict[type, Callable[[Any], Any]] = {}


def register_copier(typ: type, fn: Callable[[Any], Any]) -> None:
    """Plug-in point mirroring ``SerializationManager.Register`` for deep-copy."""
    _copiers[typ] = fn


_SHALLOW_SAFE = (int, float, str, bytes, bool, type(None), frozenset, complex)


def deep_copy(obj: Any) -> Any:
    """Copy-isolation for in-silo calls (``SerializationManager.DeepCopy``).

    Immutable wrappers, scalars, and jax/numpy arrays (immutable by API) pass
    through untouched; everything else is deep-copied.
    """
    if isinstance(obj, Immutable):
        return obj.value
    if isinstance(obj, _SHALLOW_SAFE):
        return obj
    t = type(obj)
    if t in _copiers:
        return _copiers[t](obj)
    # jax arrays are immutable; numpy arrays are not, but treating them as
    # values is the framework contract for batched payloads (they are consumed
    # by stacking, never mutated in place).
    mod = t.__module__
    if isinstance(obj, np.ndarray) or mod == "jax" or \
            mod.startswith(("jax.", "jaxlib")):
        return obj
    # Exact container types only — namedtuples / dict subclasses keep their
    # type by falling through to copy.deepcopy.
    if t is tuple:
        return tuple(deep_copy(x) for x in obj)
    if t is list:
        return [deep_copy(x) for x in obj]
    if t is dict:
        return {deep_copy(k): deep_copy(v) for k, v in obj.items()}
    return copy.deepcopy(obj)


_SCALAR_TYPES = frozenset((int, float, str, bytes, bool, type(None),
                           complex))


def copy_call_body(args: tuple, kwargs: dict) -> tuple:
    """Copy-isolate an RPC body. The dominant call shape — a few scalar
    positional args, no kwargs — shares by reference (scalars are
    immutable); anything else takes the full deep-copy walk. This is the
    hand-rolled analog of the reference's codegen'd per-signature copiers
    (SerializationManager.cs:173-201)."""
    if not kwargs:
        for a in args:
            if type(a) not in _SCALAR_TYPES:
                break
        else:
            return args, kwargs
    return deep_copy((args, kwargs))


def copy_result(result: Any) -> Any:
    """Copy-isolate an RPC result; scalars pass through untouched."""
    if type(result) in _SCALAR_TYPES:
        return result
    return deep_copy(result)


# -- external-serializer seam ------------------------------------------------
# The reference swaps whole serializers per type (Orleans.Serialization.Bond/
# Orleans.Serialization.Protobuf, registered through
# SerializationManager.cs:173-201). Here a registered codec routes its type
# through custom bytes WHEREVER values cross the wire tier: the pickle path
# uses a reducer_override, and the native hotwire codec's per-value escape
# hook goes through the same pickler — one registry covers both builds.
# Decoding reconstructs via _ext_restore (an orleans_tpu function, so the
# restricted unpickler admits it); a frame naming a codec the receiving
# process has not registered fails LOUDLY at decode.

_ext_codecs: dict[str, tuple[type, Callable[[Any], bytes],
                             Callable[[bytes], Any]]] = {}
_ext_by_type: dict[type, str] = {}
# exact-type → __reduce__-shaped fn, installed as a Pickler dispatch_table:
# C-speed per-type lookup, so unregistered payloads keep plain-pickle speed
_ext_dispatch: dict[type, Callable] = {}

# types the picklers/hotwire encode via built-in fast paths that never
# consult a dispatch table — a codec registered for one of these would be
# silently ignored, so reject it loudly instead
_EXT_UNROUTABLE = (list, dict, tuple, set, frozenset, str, bytes,
                   bytearray, int, float, bool, complex, type(None))


def register_wire_codec(name: str, typ: type,
                        encode: Callable[[Any], bytes],
                        decode: Callable[[bytes], Any]) -> None:
    """Route ``typ`` through a custom wire codec (the external-serializer
    registration seam). ``encode(obj) -> bytes`` / ``decode(bytes) -> obj``
    must be registered under the same ``name`` on every process that
    decodes such frames (exactly the reference's per-type serializer
    registration contract). Exact-type match — subclasses are not
    implicitly routed. One name per type; builtin container/scalar types
    are rejected (their fast paths bypass any dispatch).

    Scope: the WIRE/blob tier only. Same-silo calls copy-isolate through
    :func:`deep_copy`; a type that cannot survive ``copy.deepcopy`` (C
    handles, mmaps) needs a separate :func:`register_copier`."""
    if typ in _EXT_UNROUTABLE:
        raise ValueError(
            f"cannot route builtin type {typ.__name__} through a wire "
            f"codec: the pickler/hotwire fast paths never consult the "
            f"dispatch table for it")
    if name in _ext_codecs and _ext_codecs[name][0] is not typ:
        raise ValueError(f"wire codec {name!r} already registered for "
                         f"{_ext_codecs[name][0].__name__}")
    prior = _ext_by_type.get(typ)
    if prior is not None and prior != name:
        raise ValueError(
            f"{typ.__name__} already routes through codec {prior!r}; one "
            f"codec per type (unregister it first)")
    _ext_codecs[name] = (typ, encode, decode)
    _ext_by_type[typ] = name

    def reduce_(obj, _n=name, _e=encode):
        return (_ext_restore, (_n, _e(obj)))

    _ext_dispatch[typ] = reduce_


def unregister_wire_codec(name: str) -> None:
    entry = _ext_codecs.pop(name, None)
    if entry is not None and _ext_by_type.get(entry[0]) == name:
        _ext_by_type.pop(entry[0], None)
        _ext_dispatch.pop(entry[0], None)


def _ext_restore(name: str, payload: bytes) -> Any:
    entry = _ext_codecs.get(name)
    if entry is None:
        raise pickle.UnpicklingError(
            f"frame uses wire codec {name!r}, which this process has not "
            f"registered (register_wire_codec on every decoding silo)")
    return entry[2](payload)


def _pickle_dumps(obj: Any) -> bytes:
    """Pickle with the external-codec seam applied (identical to plain
    pickle.dumps when no codecs are registered)."""
    if not _ext_dispatch:
        return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    buf = io.BytesIO()
    p = pickle.Pickler(buf, protocol=pickle.HIGHEST_PROTOCOL)
    p.dispatch_table = _ext_dispatch
    p.dump(obj)
    return buf.getvalue()


def serialize(obj: Any) -> bytes:
    """Wire-tier encode (fallback-serializer slot, ``SerializationManager.cs:50``).

    Dispatches to the native ``hotwire`` codec when built (framework id
    types, scalars, containers encode ~10x faster than pickle and without
    pickle on the wire; unknown types escape per-value through the
    restricted pickler).  Falls back to plain C-speed pickle when the
    native toolchain is unavailable (``ORLEANS_TPU_NATIVE=0`` forces it).

    Codec semantics note: hotwire has no memo table — shared references
    within one payload encode as independent copies (standard wire-codec
    behavior; receiver-side aliasing was never part of the RPC contract
    since deep-copy isolation breaks it anyway), and cyclic or >200-deep
    payloads fall back to pickle below.
    """
    if _hotwire is not None:
        try:
            return _hotwire.dumps(obj)
        except ValueError:
            # cyclic / pathologically deep payload: pickle's memo handles it
            return _pickle_dumps(obj)
    return _pickle_dumps(obj)


# Module roots the wire-tier decoder will instantiate. Anything else is
# rejected — the analog of the reference's serializer registration gate
# (``SerializationManager.Register``): only known types cross the wire.
_wire_allowlist: set[str] = {
    "builtins", "collections", "datetime", "uuid", "decimal", "fractions",
    "numpy", "jax", "jaxlib", "orleans_tpu",
}

# builtins is special-cased: only value-constructor names, never eval/exec/
# getattr/__import__ (any of which turns unpickling into code execution).
_SAFE_BUILTINS = frozenset({
    "complex", "bytearray", "bytes", "dict", "frozenset", "list", "set",
    "str", "int", "float", "bool", "tuple", "range", "slice", "object",
    "Exception", "BaseException", "ValueError", "TypeError", "KeyError",
    "IndexError", "AttributeError", "RuntimeError", "OSError", "IOError",
    "TimeoutError", "StopIteration", "ArithmeticError", "ZeroDivisionError",
    "NotImplementedError", "AssertionError", "LookupError",
})


def allow_wire_modules(*prefixes: str) -> None:
    """Extend the wire-decode type allowlist (application grain payload types
    must be registered, mirroring serializer registration in the reference)."""
    _wire_allowlist.update(prefixes)


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module: str, name: str):
        root = module.split(".", 1)[0]
        if root not in _wire_allowlist:
            raise pickle.UnpicklingError(
                f"wire type {module}.{name} not in allowlist; call "
                f"allow_wire_modules({root!r}) to register it")
        if root == "builtins" and name not in _SAFE_BUILTINS:
            raise pickle.UnpicklingError(
                f"builtins.{name} is not wire-decodable")
        return super().find_class(module, name)


def _restricted_pickle_loads(data: bytes) -> Any:
    return _RestrictedUnpickler(io.BytesIO(data)).load()


def serialize_portable(obj: Any) -> bytes:
    """Encode for *durable* blobs (grain state, checkpoints): always pickle,
    so the bytes remain readable in a process where the native codec is
    unavailable (``deserialize`` dispatches on the magic byte either way).
    Wire frames die with the connection; storage blobs outlive the encoding
    process, so they must not depend on the toolchain being present.
    Registered external codecs apply here too — their registration is part
    of the deployment, same as the type allowlist."""
    return _pickle_dumps(obj)


def members_by_value(enum_cls) -> tuple:
    """Members of an IntEnum indexed by value (gaps are None) — the lookup
    shape the native decoder uses to restore enum-typed fields."""
    m = {int(e): e for e in enum_cls}
    return tuple(m.get(i) for i in range(max(m) + 1))


def deserialize(data: bytes) -> Any:
    """Wire-tier decode.  Self-describing: hotwire streams open with the
    0xA7 magic byte, pickle streams with the 0x80 PROTO opcode — either
    build can decode frames produced by the other (as long as the native
    codec is buildable for hotwire frames)."""
    if data[:1] == b"\xa7":
        if _hotwire is None:
            raise ValueError(
                "frame was encoded by the native hotwire codec but the "
                "native extension is unavailable in this process")
        return _hotwire.loads(data)
    return _restricted_pickle_loads(data)


# -- id types are immutable: deep-copy isolation passes them by reference ----
def _register_id_copiers() -> None:
    from .ids import (ActivationAddress, ActivationId, GrainId, GrainType,
                      SiloAddress)
    for _t in (GrainId, GrainType, SiloAddress, ActivationId,
               ActivationAddress):
        _copiers[_t] = lambda x: x


_register_id_copiers()


# -- native codec bootstrap --------------------------------------------------
# Imported late so orleans_tpu.core.ids is fully defined; configure hands the
# codec the id types plus the restricted pickle hooks for escape values.

def _load_hotwire():
    from ..native import load as _load_native
    hw = _load_native("_hotwire")
    if hw is None:
        return None
    from .ids import (ActivationAddress, ActivationId, GrainCategory,
                      GrainId, SiloAddress)
    cat_members = members_by_value(GrainCategory)

    def _escape_dumps(obj: Any) -> bytes:
        # per-value escape for types hotwire doesn't encode natively —
        # the external-codec seam applies here so registered types route
        # through their custom bytes under the native build too
        return _pickle_dumps(obj)

    hw.configure(GrainId, cat_members, SiloAddress, ActivationId,
                 ActivationAddress, _escape_dumps, _restricted_pickle_loads)
    return hw


_hotwire = _load_hotwire()


# ----------------------------------------------------------------------------
# Device tier: array schemas for batched payloads
# ----------------------------------------------------------------------------

@dataclass(frozen=True)
class ArrayField:
    """One field of a device payload/state schema."""

    name: str
    shape: tuple[int, ...]
    dtype: Any  # numpy dtype-like

    def zeros(self, batch: int | None = None) -> np.ndarray:
        shape = self.shape if batch is None else (batch, *self.shape)
        return np.zeros(shape, dtype=self.dtype)


class ArraySchema:
    """Fixed-layout schema: dict of named arrays with static shapes.

    The codegen analog: a grain method that runs on device declares its args
    schema once; the tick engine stacks per-message dicts into one batch
    (``stack``) and splits kernel outputs back per message (``unstack``).
    """

    def __init__(self, *fields: ArrayField):
        self.fields = fields
        self.by_name = {f.name: f for f in fields}

    @classmethod
    def of(cls, **spec) -> "ArraySchema":
        """``ArraySchema.of(x=(jnp.float32, (3,)), n=(jnp.int32, ()))``"""
        fs = []
        for name, (dtype, shape) in spec.items():
            fs.append(ArrayField(name, tuple(shape), np.dtype(dtype)))
        return cls(*fs)

    def validate(self, payload: dict) -> None:
        for f in self.fields:
            v = np.asarray(payload[f.name])
            if tuple(v.shape) != f.shape:
                raise ValueError(
                    f"field {f.name!r}: shape {v.shape} != schema {f.shape}")

    def stack(self, payloads: list[dict], pad_to: int) -> dict[str, np.ndarray]:
        """Stack N message payloads into batch arrays padded to ``pad_to``
        rows (padding keeps kernel shapes static — XLA retraces only per
        bucket size, not per batch)."""
        out = {}
        n = len(payloads)
        if n > pad_to:
            raise ValueError(
                f"batch of {n} payloads exceeds pad_to={pad_to} "
                f"(tick-engine bucketing bug)")
        for f in self.fields:
            arr = np.zeros((pad_to, *f.shape), dtype=f.dtype)
            if n:
                try:
                    arr[:n] = np.stack(
                        [np.asarray(p[f.name], dtype=f.dtype) for p in payloads])
                except ValueError as e:
                    raise ValueError(
                        f"payload field {f.name!r} does not match schema shape "
                        f"{f.shape}: {e}") from None
            out[f.name] = arr
        return out

    def unstack(self, batch: dict[str, np.ndarray], n: int) -> list[dict]:
        """Split the first ``n`` rows of a batched kernel output back into
        per-message dicts."""
        keys = list(batch.keys())
        cols = {k: np.asarray(batch[k]) for k in keys}
        return [{k: cols[k][i] for k in keys} for i in range(n)]

    def empty(self) -> dict[str, np.ndarray]:
        return {f.name: f.zeros() for f in self.fields}
