"""Message model (L1/L2 boundary).

Re-design of the reference's single unit-of-work type
(/root/reference/src/Orleans.Core/Messaging/Message.cs:12 — enums :74-101,
HeadersContainer :725) plus the response envelope (``Response.cs``).

Departures:

* In-process and intra-slice delivery never serializes: messages are plain
  Python objects handed between silo event loops (the reference deep-copies
  instead for isolation — see ``immutable`` flag).
* Batched device payloads: when a message targets a vectorized grain, ``body``
  is a (method, args-pytree) pair whose leaves are numpy/jax scalars so the
  tick engine can stack thousands of messages into one kernel launch.
"""

from __future__ import annotations

import itertools
import os
import time
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Any

from .ids import ActivationId, GrainId, SiloAddress

__all__ = [
    "Category", "Direction", "ResponseKind", "RejectionType",
    "Message", "make_request", "make_response", "make_error_response",
    "make_rejection", "recycle_message", "recycle_messages",
    "PoolDisciplineError", "set_debug_pool", "debug_pool_enabled",
    "pool_generation", "assert_live", "assert_generation",
]


class Category(IntEnum):
    """QoS classes with separate queues/draining in the reference
    (``Message.cs:74-78``, ``InboundMessageQueue.cs``, ``Silo.cs:215-217``)."""

    PING = 0
    SYSTEM = 1
    APPLICATION = 2


class Direction(IntEnum):
    """``Message.cs:80-85``."""

    REQUEST = 0
    RESPONSE = 1
    ONE_WAY = 2


class ResponseKind(IntEnum):
    """Result discriminator (``Message.ResponseTypes``, ``Message.cs:95-101``)."""

    SUCCESS = 0
    ERROR = 1
    REJECTION = 2


class RejectionType(IntEnum):
    """``Message.RejectionTypes`` (``Message.cs:87-93``)."""

    TRANSIENT = 0
    OVERLOADED = 1
    DUPLICATE_REQUEST = 2
    UNRECOVERABLE = 3
    GATEWAY_TOO_BUSY = 4
    CACHE_INVALIDATION = 5


_correlation = itertools.count(1)


@dataclass
class Message:
    """One grain message. Headers follow ``Message.HeadersContainer``
    (``Message.cs:725``) trimmed to the fields the TPU runtime consumes.

    ``__slots__`` keeps per-message overhead low on the host control plane; the
    real hot path never materializes one object per logical invocation — the
    tick engine (orleans_tpu.dispatch.tick) carries batches as arrays.
    """

    __slots__ = (
        "category", "direction", "id", "sending_silo", "sending_grain",
        "sending_activation", "target_silo", "target_grain", "target_activation",
        "interface_name", "method_name", "body", "response_kind",
        "rejection_type", "rejection_info", "forward_count", "resend_count",
        "expires_at", "call_chain", "is_read_only", "is_always_interleave",
        "is_unordered", "immutable", "cache_invalidation", "request_context",
        "is_new_placement", "transaction_info", "interface_version",
        "received_at",
        # freelist bookkeeping only — NOT dataclass fields (no annotation),
        # never cross the wire (excluded from runtime.wire._HEADER_SLOTS).
        # _pool_gen is the debug-poisoning generation counter: bumped on
        # every recycle under ORLEANS_TPU_DEBUG_POOL=1 so wire/dispatch
        # paths can assert a shell they hold was not recycled (and maybe
        # re-acquired) under them — the runtime double-check of what the
        # OTPU001 static rule proves.
        "_pool_free", "_pool_gen",
    )

    category: Category
    direction: Direction
    id: int
    sending_silo: SiloAddress | None
    sending_grain: GrainId | None
    sending_activation: ActivationId | None
    target_silo: SiloAddress | None
    target_grain: GrainId | None
    target_activation: ActivationId | None
    interface_name: str
    method_name: str
    body: Any
    response_kind: ResponseKind
    rejection_type: RejectionType | None
    rejection_info: str | None
    forward_count: int
    resend_count: int
    expires_at: float | None
    call_chain: tuple[GrainId, ...]
    is_read_only: bool
    is_always_interleave: bool
    is_unordered: bool
    immutable: bool
    cache_invalidation: list | None
    request_context: dict | None
    is_new_placement: bool
    transaction_info: Any
    # caller's compiled-against interface version (Runtime/Versions/
    # enforcement at addressing, Dispatcher.cs:725-732)
    interface_version: int
    # local monotonic arrival stamp (queue-wait attribution for tracing;
    # stamped on delivery only when a tracer is installed, never crosses
    # the wire — see runtime.wire._HEADER_SLOTS)
    received_at: float | None

    # ------------------------------------------------------------------
    @property
    def is_expired(self) -> bool:
        return self.expires_at is not None and time.monotonic() > self.expires_at

    def created_response(self, kind: ResponseKind, body: Any) -> "Message":
        """Build the response for this request, swapping sender/target
        (``MessageFactory.CreateResponseMessage``). Positional args in
        field order — this runs once per request on the hot path and the
        kwarg-matching cost of 28 fields is measurable."""
        return _fresh_message(
            self.category, Direction.RESPONSE, self.id,
            self.target_silo, self.target_grain, self.target_activation,
            self.sending_silo, self.sending_grain, self.sending_activation,
            self.interface_name, self.method_name, body,
            kind, None, None,              # response_kind, rejection x2
            0, 0, self.expires_at,         # forward, resend, expiry
            (), self.is_read_only, False,  # call_chain, read_only, interleave
            False, True, None,             # unordered, immutable, cache_inval
            None, False, self.transaction_info,  # ctx, new_placement, txn
            self.interface_version,
            None,                          # received_at (stamped on arrival)
        )


# ---------------------------------------------------------------------------
# Message freelist (the BufferPool.cs discipline applied to envelopes):
# request/response shells on the host control plane churn at call rate, and
# allocator/GC pressure was measurable in the r5 attribution. A released
# envelope re-enters service through ``_fresh_message`` (dataclass __init__
# re-run on the recycled shell — every field overwritten, so no state leaks
# between uses). ``recycle_message`` is called ONLY where the envelope's
# lifecycle provably ends (RuntimeClient.receive_response, after the caller's
# future resolves; egress shards, after an outbound response's bytes are
# produced): callers guarantee no live reference remains.
#
# Thread-safety contract (sharded egress releases from shard threads):
# RELEASE is safe from any thread — ``list.append``/``list.pop`` are
# GIL-atomic, the releasing thread is by contract the shell's LAST
# holder (so the per-shell field clears race nothing), and the capacity
# check is per-append (``len < cap`` then append can interleave across
# threads, overfilling by at most one shell per concurrent releaser —
# bounded and benign, the cap is a memory bound not an invariant).
# ACQUIRE (``_fresh_message``) stays effectively loop-side today but is
# pop-defensive so a concurrent release/acquire interleaving can never
# raise.
# ---------------------------------------------------------------------------

_MSG_POOL: list["Message"] = []
_MSG_POOL_CAP = 1024

# Debug pool-poisoning (ORLEANS_TPU_DEBUG_POOL=1): recycle_message stamps a
# per-shell generation counter and the wire/dispatch paths assert that a
# shell they hold is neither sitting in the freelist (_pool_free) nor a
# different incarnation than the one they captured (_pool_gen changed) —
# the runtime double-check of what the OTPU001 static rule proves. Off by
# default: the stamp/asserts cost nothing on the hot path when disabled
# (call sites gate on the module flag before calling in).
_DEBUG_POOL = os.environ.get("ORLEANS_TPU_DEBUG_POOL", "") not in ("", "0")


class PoolDisciplineError(AssertionError):
    """A pooled shell was used after recycle (or across a re-acquire)."""


def set_debug_pool(enabled: bool) -> bool:
    """Flip poisoning at runtime (tests); returns the previous setting."""
    global _DEBUG_POOL
    prev, _DEBUG_POOL = _DEBUG_POOL, bool(enabled)
    return prev


def debug_pool_enabled() -> bool:
    return _DEBUG_POOL


def pool_generation(m: Message) -> int:
    """Current incarnation of a shell (0 until its first debug recycle)."""
    return getattr(m, "_pool_gen", 0)


def assert_live(m: Message, where: str) -> None:
    """Poisoning check: the shell must not be in the freelist."""
    if _DEBUG_POOL and getattr(m, "_pool_free", False):
        raise PoolDisciplineError(
            f"pooled Message used after recycle at {where} "
            f"(id={getattr(m, 'id', '?')}, gen={pool_generation(m)})")


def assert_generation(m: Message, gen: int, where: str) -> None:
    """Poisoning check: the shell is live AND still the incarnation the
    caller captured — catches recycle-and-reacquire under a holder."""
    if not _DEBUG_POOL:
        return
    assert_live(m, where)
    if pool_generation(m) != gen:
        raise PoolDisciplineError(
            f"pooled Message recycled under its holder at {where} "
            f"(captured gen {gen}, now {pool_generation(m)})")


def _fresh_message(*fields) -> Message:
    pool = _MSG_POOL
    if pool:
        try:
            m = pool.pop()
        except IndexError:  # raced a concurrent acquirer: allocate
            m = None
        if m is not None:
            m._pool_free = False
            m.__init__(*fields)
            return m
    m = Message(*fields)
    m._pool_free = False
    m._pool_gen = 0
    return m


def recycle_message(m: Message) -> None:
    """Return a dead envelope to the freelist. Idempotent (double release
    is a no-op via ``_pool_free`` — the STATIC double-release check is
    OTPU001's job); drops the shell when the pool is full. Reference-
    carrying fields are cleared so a pooled shell cannot pin user payloads
    or context dicts alive. Callable from any thread (see the freelist
    thread-safety contract above): the capacity check is per-append, so
    concurrent releasers can overfill the pool by at most one shell
    each — a memory bound, not an invariant."""
    if getattr(m, "_pool_free", False):
        return
    pool_full = len(_MSG_POOL) >= _MSG_POOL_CAP
    if pool_full and not _DEBUG_POOL:
        return
    if _DEBUG_POOL:
        # stamp even when the shell is DROPPED (pool at cap): poisoning
        # must keep detecting use-after-recycle on the busiest paths,
        # which are exactly the ones that fill the pool. A dropped shell
        # never re-enters service, so leaving it marked free is correct —
        # any later touch is the bug the mode exists to catch.
        m._pool_gen = pool_generation(m) + 1
    m._pool_free = True
    m.body = None
    m.request_context = None
    m.transaction_info = None
    m.cache_invalidation = None
    m.call_chain = ()
    if not pool_full:
        _MSG_POOL.append(m)


def recycle_messages(msgs) -> None:
    """Batch twin of :func:`recycle_message` — ONE release sweep for the
    envelopes a batched response correlation retires together
    (``RuntimeClient.receive_response_batch``: two envelopes per RPC at
    batch rate, where the per-call function overhead was the point of
    batching), and for the egress shards' encode-then-recycle sweep
    (shard-thread callers — the capacity check below is per-append, not
    a precomputed room count, so concurrent sweeps stay bounded; see
    the freelist thread-safety contract above). Semantics are identical
    per envelope: idempotent via ``_pool_free``, reference-carrying
    fields cleared, debug-pool generation stamped even when the full
    pool drops the shell."""
    pool = _MSG_POOL
    debug = _DEBUG_POOL
    cap = _MSG_POOL_CAP
    for m in msgs:
        if getattr(m, "_pool_free", False):
            continue
        room = len(pool) < cap
        if not room and not debug:
            continue
        if debug:
            m._pool_gen = pool_generation(m) + 1
        m._pool_free = True
        m.body = None
        m.request_context = None
        m.transaction_info = None
        m.cache_invalidation = None
        m.call_chain = ()
        if room:
            pool.append(m)


def make_request(
    *,
    target_grain: GrainId,
    interface_name: str,
    method_name: str,
    body: Any,
    category: Category = Category.APPLICATION,
    direction: Direction = Direction.REQUEST,
    sending_silo: SiloAddress | None = None,
    sending_grain: GrainId | None = None,
    sending_activation: ActivationId | None = None,
    target_silo: SiloAddress | None = None,
    timeout: float | None = 30.0,
    call_chain: tuple[GrainId, ...] = (),
    is_read_only: bool = False,
    is_always_interleave: bool = False,
    immutable: bool = False,
    request_context: dict | None = None,
    interface_version: int = 0,
) -> Message:
    """Request factory (``MessageFactory.CreateMessage``). Default 30 s expiry
    mirrors ``MessagingOptions.ResponseTimeout``. Positional construction in
    field order (see created_response)."""
    return _fresh_message(
        category, direction, next(_correlation),
        sending_silo, sending_grain, sending_activation,
        target_silo, target_grain, None,
        interface_name, method_name, body,
        ResponseKind.SUCCESS, None, None,
        0, 0,
        (time.monotonic() + timeout) if timeout is not None else None,
        call_chain, is_read_only, is_always_interleave,
        False, immutable, None,
        request_context, False, None,
        interface_version,
        None,
    )


def make_request_fast(
    category, direction, sending_silo, sending_grain, sending_activation,
    target_silo, target_grain, interface_name, method_name, body,
    expires_at, call_chain, is_read_only, is_always_interleave,
    request_context, interface_version,
) -> Message:
    """Positional hot-path twin of :func:`make_request` (the RPC engine
    builds one Message per call; 28 kwargs are measurable there). The
    field list lives here, beside the dataclass, so reordering Message
    fields has exactly one positional construction site per shape to
    update (this, make_request, created_response)."""
    return _fresh_message(
        category, direction, next(_correlation),
        sending_silo, sending_grain, sending_activation,
        target_silo, target_grain, None,
        interface_name, method_name, body,
        ResponseKind.SUCCESS, None, None,
        0, 0, expires_at,
        call_chain, is_read_only, is_always_interleave,
        False, False, None,
        request_context, False, None,
        interface_version,
        None,
    )


def make_response(request: Message, result: Any) -> Message:
    return request.created_response(ResponseKind.SUCCESS, result)


def make_error_response(request: Message, exc: BaseException) -> Message:
    return request.created_response(ResponseKind.ERROR, exc)


def make_rejection(request: Message, rtype: RejectionType, info: str) -> Message:
    msg = request.created_response(ResponseKind.REJECTION, None)
    msg.rejection_type = rtype
    msg.rejection_info = info
    return msg
