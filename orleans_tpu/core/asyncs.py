"""Async coordination utilities.

Re-design of /root/reference/src/Orleans.Core/Async/ (1,342 LoC):
``AsyncExecutorWithRetries`` (backoff retry), ``BatchWorker`` (coalesced
background work), ``AsyncSerialExecutor`` (non-reentrant serial execution of
queued closures), ``AsyncPipeline`` (bounded-concurrency task pump). These
are asyncio-native rather than Task/TPL ports: the scheduler they cooperate
with is the event loop, not a custom thread pool.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Any, Awaitable, Callable, TypeVar

log = logging.getLogger("orleans.async")

T = TypeVar("T")

__all__ = [
    "retry", "ExponentialBackoff", "BatchWorker", "AsyncSerialExecutor",
    "AsyncPipeline",
]


class ExponentialBackoff:
    """Jittered exponential backoff delays (``ExponentialBackoff`` struct)."""

    def __init__(self, min_delay: float = 0.05, max_delay: float = 5.0,
                 factor: float = 2.0, jitter: float = 0.2):
        self.min_delay = min_delay
        self.max_delay = max_delay
        self.factor = factor
        self.jitter = jitter

    def delay(self, attempt: int) -> float:
        base = min(self.max_delay, self.min_delay * (self.factor ** attempt))
        return base * (1.0 + self.jitter * (2 * random.random() - 1.0))


async def retry(
    fn: Callable[[int], Awaitable[T]] | Callable[[], Awaitable[T]],
    *,
    max_attempts: int = 5,
    backoff: ExponentialBackoff | None = None,
    retry_on: Callable[[Exception], bool] | type | tuple = Exception,
) -> T:
    """``AsyncExecutorWithRetries.ExecuteWithRetries``: run ``fn`` until it
    succeeds, retrying failures that match ``retry_on`` with backoff.

    ``fn`` may accept the attempt index (the reference passes the retry
    counter to the callable) or no arguments.
    """
    backoff = backoff or ExponentialBackoff()
    if isinstance(retry_on, (type, tuple)):
        exc_types = retry_on
        should_retry = lambda e: isinstance(e, exc_types)  # noqa: E731
    else:
        should_retry = retry_on
    import inspect
    # pass the attempt index only to callables with a REQUIRED positional
    # parameter — optional/keyword-only params (timeouts, partials) must not
    # silently receive the counter
    wants_attempt = any(
        p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                   inspect.Parameter.POSITIONAL_OR_KEYWORD)
        and p.default is inspect.Parameter.empty
        for p in inspect.signature(fn).parameters.values())
    last: Exception | None = None
    for attempt in range(max_attempts):
        try:
            return await (fn(attempt) if wants_attempt else fn())
        except Exception as e:  # noqa: BLE001 — filtered by should_retry
            last = e
            if not should_retry(e) or attempt == max_attempts - 1:
                raise
            await asyncio.sleep(backoff.delay(attempt))
    raise last  # pragma: no cover — loop always returns or raises


class BatchWorker:
    """Coalesced background work (``BatchWorker``/``BatchWorkerFromDelegate``):
    any number of ``notify()`` calls while a batch is running fold into
    exactly one more run of ``work`` afterwards. The pattern behind
    write-behind flushing, directory maintenance, and log-view workers."""

    def __init__(self, work: Callable[[], Awaitable[None]]):
        self._work = work
        self._more = asyncio.Event()
        self._idle = asyncio.Event()
        self._idle.set()
        self._task: asyncio.Task | None = None
        self._closed = False

    def notify(self) -> None:
        """Request (another) run of the work callback."""
        if self._closed:
            raise RuntimeError("BatchWorker is closed")
        self._more.set()
        self._idle.clear()
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        try:
            while self._more.is_set():
                self._more.clear()
                try:
                    await self._work()
                except Exception:  # noqa: BLE001 — worker survives failures
                    log.exception("BatchWorker work() failed")
        finally:
            if not self._more.is_set():
                self._idle.set()

    async def wait_idle(self) -> None:
        """Wait until all notified work has been executed
        (``WaitForCurrentWorkToBeServiced``)."""
        await self._idle.wait()

    async def notify_and_wait(self) -> None:
        self.notify()
        await self.wait_idle()

    def close(self) -> None:
        self._closed = True
        if self._task is not None and not self._task.done():
            self._task.cancel()


class AsyncSerialExecutor:
    """Serial, non-reentrant execution of queued closures
    (``AsyncSerialExecutor``): submissions run strictly one at a time in
    submission order, each submission's result awaitable by its caller."""

    def __init__(self) -> None:
        self._queue: asyncio.Queue[tuple[Callable, asyncio.Future]] = \
            asyncio.Queue()
        self._pump: asyncio.Task | None = None

    def submit(self, fn: Callable[[], Awaitable[T]]) -> "asyncio.Future[T]":
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._queue.put_nowait((fn, fut))
        if self._pump is None or self._pump.done():
            self._pump = asyncio.get_running_loop().create_task(self._run())
        return fut

    async def execute(self, fn: Callable[[], Awaitable[T]]) -> T:
        return await self.submit(fn)

    async def _run(self) -> None:
        while not self._queue.empty():
            fn, fut = self._queue.get_nowait()
            if fut.cancelled():
                continue
            try:
                result = await fn()
            except Exception as e:  # noqa: BLE001 — delivered to the caller
                if not fut.done():
                    fut.set_exception(e)
            else:
                if not fut.done():
                    fut.set_result(result)


class AsyncPipeline:
    """Bounded-concurrency task pump (``AsyncPipeline``): ``add`` blocks when
    ``capacity`` tasks are in flight — the backpressure primitive the
    reference uses for bulk storage/stream operations."""

    def __init__(self, capacity: int = 10):
        self.capacity = capacity
        self._sem = asyncio.Semaphore(capacity)
        self._tasks: set[asyncio.Task] = set()
        self._errors: list[Exception] = []

    async def add(self, coro: Awaitable[Any]) -> None:
        await self._sem.acquire()
        task = asyncio.get_running_loop().create_task(self._wrap(coro))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _wrap(self, coro: Awaitable[Any]) -> None:
        try:
            await coro
        except Exception as e:  # noqa: BLE001 — surfaced by wait_complete
            self._errors.append(e)
        finally:
            self._sem.release()

    async def wait_complete(self) -> None:
        """Drain the pipeline; raises the first captured error, if any."""
        while self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)
        if self._errors:
            err = self._errors[0]
            self._errors.clear()
            raise err

    @property
    def count(self) -> int:
        return len(self._tasks)
