"""Framework exception taxonomy.

Mirrors the reference's public exception surface
(/root/reference/src/Orleans.Core.Abstractions/Core/ — ``OrleansException``,
``SiloUnavailableException``, ``InconsistentStateException`` in
``Core/Providers``, ``Catalog.NonExistentActivationException`` Catalog.cs:29).
"""

from __future__ import annotations

__all__ = [
    "OrleansError", "SiloUnavailableError", "GrainCallTimeoutError",
    "NonExistentActivationError", "InconsistentStateError", "DeadlockError",
    "GatewayTooBusyError", "GrainOverloadedError", "RejectionError",
    "ClusterMembershipError", "ReminderError", "StreamError",
    "TransactionError", "TransactionAbortedError", "ConfigurationError",
]


class OrleansError(Exception):
    """Base for all framework errors (``OrleansException``)."""


class TransientPlacementError(OrleansError):
    """Addressing failed for a reason expected to heal shortly (e.g. a
    joining silo's type map has not arrived yet): surfaced to callers as
    a TRANSIENT rejection so the resend machinery retries, instead of a
    hard error."""


class ConfigurationError(OrleansError):
    """Invalid options rejected by a validator
    (``OrleansConfigurationException``, Core/Configuration/Validators/)."""


class SiloUnavailableError(OrleansError):
    """Target silo is dead/unreachable; outstanding calls are broken with this
    (``InsideRuntimeClient.BreakOutstandingMessagesToDeadSilo``,
    InsideRuntimeClient.cs:726)."""


class GrainCallTimeoutError(OrleansError, TimeoutError):
    """Response not received before ResponseTimeout (``CallbackData`` timeout)."""


class NonExistentActivationError(OrleansError):
    """Message addressed to an activation that no longer exists
    (``Catalog.NonExistentActivationException``, Catalog.cs:29); triggers
    re-address + retry at the caller."""

    def __init__(self, msg: str, *, is_stateless_worker: bool = False):
        super().__init__(msg)
        self.is_stateless_worker = is_stateless_worker


class InconsistentStateError(OrleansError):
    """Storage etag mismatch; the activation is deactivated and rebuilt from
    storage on next call (``InsideRuntimeClient.cs:390-402``)."""

    def __init__(self, msg: str, stored_etag: str | None = None,
                 current_etag: str | None = None):
        super().__init__(msg)
        self.stored_etag = stored_etag
        self.current_etag = current_etag


class DeadlockError(OrleansError):
    """Call-chain cycle detected (``Dispatcher.CheckDeadlock``,
    Dispatcher.cs:364-392)."""


class GatewayTooBusyError(OrleansError):
    """Gateway load shedding (``LoadSheddingOptions``)."""


class GrainOverloadedError(OrleansError):
    """Per-activation overload rejection (``ActivationData.CheckOverloaded``,
    ActivationData.cs:616 → Dispatcher.cs:433-439)."""


class RejectionError(OrleansError):
    """Generic message rejection carrying the rejection info string."""


class ClusterMembershipError(OrleansError):
    """Membership table CAS conflict / protocol violation."""


class ReminderError(OrleansError):
    pass


class StreamError(OrleansError):
    pass


class TransactionError(OrleansError):
    pass


class TransactionAbortedError(TransactionError):
    pass


class TransactionConflictError(TransactionAbortedError):
    """Wound-wait entry conflict: this transaction gave way — wounded by an
    older transaction, or timed out waiting — before running any doomed
    2PC work. Always retryable — the root @transactional scope retries with
    the transaction's original priority timestamp so it ages into the
    winner (livelock-free)."""
