"""Identity & hashing layer (L0).

TPU-native re-design of the reference's ID system
(/root/reference/src/Orleans.Core.Abstractions/IDs/ — ``UniqueKey.cs:9,28-31``,
``GrainId.cs:199``, ``SiloAddress.cs``, ``ActivationAddress.cs``).

Design departures from the reference:

* Keys are plain Python data (int / str / uuid bytes) carried alongside a stable
  64-bit ``uniform_hash`` that is *device-friendly*: every ID can be projected to an
  ``int64`` so the directory, ring placement, and mesh-shard routing can all run as
  integer math inside jitted kernels. The reference's Jenkins hash
  (``UniqueKey.cs:272-286``) plays the same role host-side only.
* No interning table (``Internal/Interner.cs``): frozen dataclasses with cached
  hashes are cheap enough in CPython and hashable by construction.
"""

from __future__ import annotations

import hashlib
import os as _os
import random as _random
import uuid
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Union

__all__ = [
    "GrainCategory",
    "GrainType",
    "GrainId",
    "SiloAddress",
    "ActivationId",
    "ActivationAddress",
    "stable_hash64",
    "stable_hash32",
    "type_code_of",
]

def stable_hash64(data: Union[bytes, str, int]) -> int:
    """Deterministic 64-bit hash, stable across processes and hosts.

    Fills the role of ``JenkinsHash``/``GetUniformHashCode`` in the reference
    (``UniqueKey.cs:272-286``): directory sharding, ring placement, and sender-lane
    picking all key off this value. Returns a non-negative int that fits int64
    (top bit cleared so it round-trips through jnp.int64 without sign surprises).
    """
    if isinstance(data, int):
        data = data.to_bytes((data.bit_length() + 8) // 8 + 1, "little", signed=True)
    elif isinstance(data, str):
        data = data.encode("utf-8")
    h = hashlib.blake2b(data, digest_size=8).digest()
    return int.from_bytes(h, "little") & ((1 << 63) - 1)


def stable_hash32(data: Union[bytes, str, int]) -> int:
    """32-bit variant (the reference's uniform hash width)."""
    return stable_hash64(data) & 0xFFFFFFFF


def type_code_of(name: str) -> int:
    """Stable 32-bit type code for a grain class/interface name.

    The reference embeds a type code computed by codegen into the key
    (``UniqueKey.cs:28-31``); here it is derived from the fully-qualified class
    name so that independently-started silos agree without a codegen step.
    """
    return stable_hash32("grain-type:" + name)


class GrainCategory(IntEnum):
    """Mirrors UniqueKey categories (``UniqueKey.cs:17-24``), trimmed to what the
    TPU runtime distinguishes."""

    GRAIN = 1          # ordinary application grain
    SYSTEM_TARGET = 2  # per-silo pseudo-grain at a well-known id
    CLIENT = 3         # client observer endpoint
    SYSTEM_GRAIN = 4   # runtime-owned grain (e.g. membership dev table)


@dataclass(frozen=True)
class GrainType:
    """A grain class identity: name + stable type code."""

    name: str
    type_code: int

    @classmethod
    def of(cls, name: str) -> "GrainType":
        return cls(name=name, type_code=type_code_of(name))

    def __repr__(self) -> str:
        return f"GrainType({self.name})"


KeyType = Union[int, str, bytes]


_grain_id_intern: dict = {}
_INTERN_LIMIT = 1 << 17


def _rebuild_grain_id(category: int, type_code: int, key,
                      key_ext, hash64: int) -> "GrainId":
    """Wire-decode constructor for GrainId.__reduce__ (hash precomputed)."""
    return GrainId(GrainCategory(category), type_code, key, key_ext, hash64)


@dataclass(frozen=True)
class GrainId:
    """Grain identity = (category, type_code, primary key [, key extension]).

    The reference packs this into a 128-bit UniqueKey + 64-bit type-code word
    (``UniqueKey.cs:9,28-31``); we keep the key in native Python form plus a
    precomputed 64-bit uniform hash for device-side routing.
    """

    category: GrainCategory
    type_code: int
    key: KeyType
    key_ext: str | None = None
    _hash64: int = field(default=-1, compare=False, repr=False)

    def __post_init__(self) -> None:
        if self._hash64 < 0:
            payload = b"%d|%d|" % (self.category, self.type_code)
            k = self.key
            if isinstance(k, int):
                kb = k.to_bytes((k.bit_length() + 8) // 8 + 1, "little", signed=True)
                payload += b"i%d:" % len(kb) + kb
            elif isinstance(k, str):
                kb = k.encode("utf-8")
                payload += b"s%d:" % len(kb) + kb
            else:
                payload += b"b%d:" % len(k) + k
            if self.key_ext is not None:
                eb = self.key_ext.encode("utf-8")
                payload += b"e%d:" % len(eb) + eb
            object.__setattr__(self, "_hash64", stable_hash64(payload))

    # -- factory helpers ---------------------------------------------------
    @classmethod
    def for_grain(cls, grain_type: GrainType, key: KeyType,
                  key_ext: str | None = None) -> "GrainId":
        # interning (the reference's Interner.cs): grain ids are built on
        # every get_grain call and the hash in __post_init__ is the single
        # hottest id-layer cost — reuse frozen instances for hashable keys
        if isinstance(key, (int, str)):
            k = (grain_type.type_code, key, key_ext)
            cached = _grain_id_intern.get(k)
            if cached is None:
                cached = cls(GrainCategory.GRAIN, grain_type.type_code,
                             key, key_ext)
                if len(_grain_id_intern) >= _INTERN_LIMIT:
                    _grain_id_intern.clear()  # bounded; ids are cheap to remake
                _grain_id_intern[k] = cached
            return cached
        return cls(GrainCategory.GRAIN, grain_type.type_code, key, key_ext)

    @classmethod
    def for_guid(cls, grain_type: GrainType, guid: uuid.UUID) -> "GrainId":
        return cls(GrainCategory.GRAIN, grain_type.type_code, guid.bytes)

    @classmethod
    def system_target(cls, type_code: int, silo: "SiloAddress") -> "GrainId":
        """System targets are per-silo well-known ids (``Constants.cs`` +
        ``Silo.RegisterSystemTarget``, ``Silo.cs:816-820``)."""
        return cls(GrainCategory.SYSTEM_TARGET, type_code, silo.uniform_hash)

    @classmethod
    def client(cls, client_id: str) -> "GrainId":
        return cls(GrainCategory.CLIENT, 0, client_id)

    # -- hashing -----------------------------------------------------------
    @property
    def uniform_hash(self) -> int:
        """64-bit uniform hash — the routing key for directory partitioning and
        ring placement (role of ``GetUniformHashCode``)."""
        return self._hash64

    @property
    def consistent_hash(self) -> int:
        """Hash used for ring position (reference keeps a separate consistent
        hash; one good 64-bit hash serves both here)."""
        return self._hash64

    def __hash__(self) -> int:
        # grain ids key every hot dict (catalog, directory, caches); the
        # precomputed 64-bit hash beats re-hashing the field tuple per op
        return self._hash64

    def __reduce__(self):
        # compact wire form: a 5-tuple of primitives (the default frozen-
        # dataclass pickling writes the field-name dict + the enum by
        # reference — ~3x the bytes and time). Carrying _hash64 skips the
        # __post_init__ re-hash on decode.
        return (_rebuild_grain_id, (int(self.category), self.type_code,
                                    self.key, self.key_ext, self._hash64))

    def is_client(self) -> bool:
        return self.category == GrainCategory.CLIENT

    def is_system_target(self) -> bool:
        return self.category == GrainCategory.SYSTEM_TARGET

    def __str__(self) -> str:
        ext = f"+{self.key_ext}" if self.key_ext else ""
        return f"grain/{self.category.name.lower()}/{self.type_code:08x}/{self.key!r}{ext}"


@dataclass(frozen=True)
class SiloAddress:
    """Silo identity: (host endpoint, generation).

    Mirrors ``SiloAddress.cs`` — generation (an epoch stamp) distinguishes a
    restarted silo at the same endpoint. On TPU, a "silo" is one host process
    owning a set of mesh coordinates; ``mesh_index`` is its rank along the
    cluster mesh axis (-1 for clients / not-yet-joined).
    """

    host: str
    port: int
    generation: int
    mesh_index: int = -1
    _uh: int = field(default=-1, compare=False, repr=False)

    def __post_init__(self) -> None:
        if self._uh < 0:
            object.__setattr__(self, "_uh", stable_hash64(
                f"silo|{self.host}|{self.port}|{self.generation}"))

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def uniform_hash(self) -> int:
        return self._uh

    def __hash__(self) -> int:
        return self._uh

    def __reduce__(self):
        return (SiloAddress, (self.host, self.port, self.generation,
                              self.mesh_index, self._uh))

    def same_endpoint(self, other: "SiloAddress") -> bool:
        return self.host == other.host and self.port == other.port

    def is_successor_of(self, other: "SiloAddress") -> bool:
        return self.same_endpoint(other) and self.generation > other.generation

    def __str__(self) -> str:
        return f"S{self.host}:{self.port}@{self.generation}"


_activation_rng = _random.Random(_os.urandom(16))


@dataclass(frozen=True)
class ActivationId:
    """Unique id of one in-memory activation of a grain (``ActivationId.cs``).

    Ids are drawn from a per-process CSPRNG-seeded stream (the reference uses
    GUIDs) so they are unique cluster-wide, including across forked silo
    processes. For device-resident (vectorized) activations the id doubles as
    the stable identity across slot moves; the (table epoch, slot) pair lives
    in the catalog, not here.
    """

    value: int

    @classmethod
    def new(cls) -> "ActivationId":
        return cls(_activation_rng.getrandbits(63))

    def __reduce__(self):
        return (ActivationId, (self.value,))

    def __str__(self) -> str:
        return f"act-{self.value:016x}"


@dataclass(frozen=True)
class ActivationAddress:
    """Full address of an activation: silo + grain + activation
    (``ActivationAddress.cs``)."""

    silo: SiloAddress
    grain: GrainId
    activation: ActivationId

    def __str__(self) -> str:
        return f"[{self.grain} @ {self.silo} / {self.activation}]"
