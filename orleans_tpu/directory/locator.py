"""Distributed grain locator: ring-partitioned directory + placement.

Re-design of /root/reference/src/Orleans.Runtime/GrainDirectory/:
``LocalGrainDirectory.cs:16`` (ring :23, CalculateTargetSilo :477-546,
RegisterAsync :576, UnregisterAsync :673, LookupAsync :878),
``GrainDirectoryPartition.cs:207`` (AddSingleActivation :304 — first-wins
registration), the LRU cache (``LRUBasedGrainDirectoryCache.cs``) with
invalidation on forward, ``RemoteGrainDirectory.cs`` (directory ops as
system-target messages), and ``GrainDirectoryHandoffManager.cs`` (partition
re-ranging on membership change).

One DistributedLocator per silo replaces SingleSiloLocator when the silo
joins a multi-silo fabric. Directory ownership: ``ring.owner(grain_hash)``;
ops for grains owned elsewhere become SYSTEM-category messages to the
owner's DirectoryTarget.
"""

from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING

from ..core.ids import ActivationAddress, GrainId, SiloAddress
from ..core.message import Category, Message
from ..placement import PlacementManager
from .ring import ConsistentRing

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.directory")

DIRECTORY_TARGET = "DirectoryTarget"
CACHE_SIZE_DEFAULT = 100_000


class DirectoryTarget:
    """Per-silo directory system target (RemoteGrainDirectory.cs:110): the
    remote surface of this silo's partition."""

    _activation = None

    def __init__(self, locator: "DistributedLocator"):
        self.locator = locator

    async def dir_lookup_or_place(self, grain_id: GrainId,
                                  placement: str | None,
                                  requester: SiloAddress,
                                  interface_name: str | None = None,
                                  requested_version: int = 0):
        return self.locator.local_lookup_or_place(
            grain_id, placement, requester, interface_name,
            requested_version)

    async def dir_register(self, address: ActivationAddress):
        return self.locator.local_register(address)

    async def dir_migrate_register(self, address: ActivationAddress,
                                   prev_activation):
        return self.locator.local_migrate_register(address, prev_activation)

    async def dir_cache_invalidate(self, grain_id: GrainId) -> bool:
        """Drop a stale LRU cache entry on THIS silo — the receive half of
        invalidation-on-forward (the reference piggybacks the invalidation
        on the forwarded message's response path; here it is an explicit
        one-way system message from the forwarding silo). Without this, a
        sender whose cache points at an activation's PREVIOUS silo (e.g.
        after a live migration) pays a forward hop on every message until
        the entry's TTL expires."""
        self.locator.cache.pop(grain_id, None)
        return True

    async def dir_unregister(self, address: ActivationAddress):
        self.locator.local_unregister(address)
        return True

    async def dir_drop_stale(self, grain_id: GrainId, silo: SiloAddress,
                             live_activations: list) -> bool:
        """Drop a registration that points at ``silo`` unless it names one
        of the activations ``silo`` reports live — the directory half of
        UnregisterAfterNonexistingActivation (Catalog.cs:29 rejection →
        LocalGrainDirectory cleanup): without this, an entry left behind
        by a dead activation (e.g. planted by a re-range handoff that
        raced a deactivation) ping-pongs every lookup into the forward
        limit forever."""
        cur = self.locator.partition.get(grain_id)
        if cur is not None and cur.silo == silo and \
                cur.activation not in live_activations:
            self.locator.partition.pop(grain_id, None)
            self.locator.cache.pop(grain_id, None)
            return True
        return False

    async def dir_handoff(self, entries: list):
        """Bulk-receive partition entries from a re-ranging peer
        (GrainDirectoryHandoffManager)."""
        for addr in entries:
            self.locator.local_register(addr)
        return True

    async def dir_lookup_many(self, grain_ids: list) -> list:
        """Batched owner lookup for the adaptive-cache maintainer
        (AdaptiveDirectoryCacheMaintainer.cs:243 batches per owner):
        current hosting silo per grain, None where no live registration
        exists."""
        out = []
        for gid in grain_ids:
            reg = self.locator.partition.get(gid)
            out.append(reg.silo if reg is not None
                       and reg.silo in self.locator.alive_set else None)
        return out


class DistributedLocator:
    """Implements the silo locator protocol over a ring-partitioned
    directory (drop-in replacement for SingleSiloLocator)."""

    def __init__(self, silo: "Silo"):
        self.silo = silo
        self.ring = ConsistentRing([silo.silo_address])
        self.alive_set: set[SiloAddress] = {silo.silo_address}
        self.alive_list: list[SiloAddress] = [silo.silo_address]
        self.partition: dict[GrainId, ActivationAddress] = {}
        from .adaptive_cache import AdaptiveDirectoryCache
        self.cache = AdaptiveDirectoryCache(
            silo.config.directory_cache_size,
            initial_ttl=silo.config.directory_cache_initial_ttl,
            max_ttl=silo.config.directory_cache_max_ttl)
        self.cache_size = silo.config.directory_cache_size
        self._maintainer_task = None  # started by Silo.start
        self.placement = PlacementManager(load_of=self._load_of)
        from ..versions import VersionManager
        from ..versions.manager import TYPE_MANAGER_TARGET
        self.versions = VersionManager(silo)
        self.target = DirectoryTarget(self)
        self.target_id = silo.register_system_target(
            self.target, DIRECTORY_TARGET)
        silo.register_system_target(self.versions.target,
                                    TYPE_MANAGER_TARGET)

    # ------------------------------------------------------------------
    def _load_of(self, silo: SiloAddress) -> int:
        """Activation-count stats feed: prefer the DeploymentLoadPublisher
        view (cross-host capable); fall back to the in-proc fabric shortcut
        of reading the peer catalog directly."""
        publisher = getattr(self.silo, "load_publisher", None)
        if publisher is not None:
            v = publisher.load_of(silo)
            if v is not None:
                return v
        s = self.silo.fabric.silos.get(silo)
        return s.catalog.activation_count() if s is not None else 1 << 30

    def _alive(self) -> list[SiloAddress]:
        return self.alive_list or [self.silo.silo_address]

    def _target_ref(self, silo: SiloAddress, method: str, *args):
        """Invoke a directory op on a peer's system target."""
        gid = GrainId.system_target(
            _dir_type_code(), silo)
        return self.silo.runtime_client.send_request(
            target_grain=gid, grain_class=DirectoryTarget,
            interface_name="DirectoryTarget", method_name=method,
            args=args, kwargs={}, target_silo=silo,
            category=Category.SYSTEM)

    # ------------------------------------------------------------------
    # Locator protocol
    # ------------------------------------------------------------------
    def try_locate_sync(self, msg: Message, grain_class: type | None
                        ) -> SiloAddress | None:
        """Synchronously-resolvable addressing: system targets, stateless
        workers, cache hits, and locally-owned directory partitions. The
        dispatcher uses this to skip a task round trip per send — only the
        remote-owner directory hop needs the async path. Returns None when
        a remote hop is required."""
        grain_id = msg.target_grain
        if grain_id.is_system_target() or grain_id.is_client():
            return msg.target_silo or self.silo.silo_address
        if grain_class is None:
            grain_class = self.silo.registry.resolve(msg.interface_name)
        if grain_class is not None and \
                getattr(grain_class, "__orleans_stateless_worker__", 0):
            return self.silo.silo_address  # stateless workers host locally
        cached = self.cache.get(grain_id)  # TTL-aware: expired reads miss
        if cached is not None and cached in self.alive_set:
            return cached
        owner = self.ring.owner(grain_id.uniform_hash) or self.silo.silo_address
        if owner != self.silo.silo_address:
            return None  # remote directory hop — async path
        placement_name = getattr(grain_class, "__orleans_placement__",
                                 None) if grain_class else None
        # traced directory work: the remote hop records as a client span
        # of the DirectoryTarget RPC; this locally-owned lookup/placement
        # would otherwise be invisible to the trace ("directory lookup on
        # first call" must show up either way)
        dspan = None
        tracer = getattr(self.silo, "tracer", None)
        if tracer is not None:
            from ..observability.tracing import context_from_headers
            hdr = context_from_headers(msg.request_context)
            if hdr is not None:
                dspan = tracer.open("directory.lookup_or_place",
                                    "directory", hdr[0], hdr[1])
        try:
            silo, is_new = self.local_lookup_or_place(
                grain_id, placement_name, self.silo.silo_address,
                msg.interface_name, msg.interface_version)
        except BaseException:
            if dspan is not None:
                tracer.close(dspan, error=True)
            raise
        if dspan is not None:
            tracer.close(dspan, placed=is_new, host=str(silo))
        msg.is_new_placement = is_new
        self._cache_put(grain_id, silo)
        return silo

    async def locate(self, msg: Message, grain_class: type | None) -> SiloAddress:
        """AddressMessage:715 — resolve the hosting silo for a request."""
        target = self.try_locate_sync(msg, grain_class)
        if target is not None:
            return target
        grain_id = msg.target_grain
        if grain_class is None:
            grain_class = self.silo.registry.resolve(msg.interface_name)
        placement_name = getattr(grain_class, "__orleans_placement__",
                                 None) if grain_class else None
        owner = self.ring.owner(grain_id.uniform_hash) or self.silo.silo_address
        silo, is_new = await self._target_ref(
            owner, "dir_lookup_or_place", grain_id, placement_name,
            self.silo.silo_address, msg.interface_name,
            msg.interface_version)
        msg.is_new_placement = is_new
        self._cache_put(grain_id, silo)
        return silo

    def should_host(self, grain_id: GrainId, grain_class: type,
                    msg: Message) -> bool:
        if getattr(grain_class, "__orleans_stateless_worker__", 0):
            return True
        if msg.is_new_placement:
            return True
        reg = self.partition.get(grain_id)
        return reg is not None and reg.silo == self.silo.silo_address

    async def register(self, address: ActivationAddress
                       ) -> ActivationAddress | None:
        """RegisterAsync:576 → first-wins AddSingleActivation on the owner."""
        owner = self.ring.owner(address.grain.uniform_hash)
        if owner is None or owner == self.silo.silo_address:
            return self.local_register(address)
        return await self._target_ref(owner, "dir_register", address)

    async def unregister(self, address: ActivationAddress) -> None:
        owner = self.ring.owner(address.grain.uniform_hash)
        self.cache.pop(address.grain, None)
        if owner is None or owner == self.silo.silo_address:
            self.local_unregister(address)
        else:
            try:
                await self._target_ref(owner, "dir_unregister", address)
            except Exception:  # noqa: BLE001 — owner may be mid-death
                log.debug("remote unregister failed for %s", address.grain)

    async def migrate_register(self, address: ActivationAddress,
                               prev_activation) -> ActivationAddress:
        """Re-register a grain mid-migration: REPLACE the registration the
        migrating activation holds with the new address (ordinary
        ``register`` is first-wins and would keep pointing at the source).
        ``prev_activation``: the ActivationId being migrated away — the
        guard that a racing re-creation's registration is never usurped.
        Returns the winning address (≠ ``address`` means the migration
        lost and must abort)."""
        owner = self.ring.owner(address.grain.uniform_hash)
        self.cache.pop(address.grain, None)
        if owner is None or owner == self.silo.silo_address:
            return self.local_migrate_register(address, prev_activation)
        return await self._target_ref(owner, "dir_migrate_register",
                                      address, prev_activation)

    def invalidate_cache(self, grain_id: GrainId) -> None:
        self.cache.pop(grain_id, None)

    def notify_cache_invalidate(self, peer: SiloAddress,
                                grain_id: GrainId) -> None:
        """Invalidation-on-forward, cross-silo half: fire-and-forget a
        cache drop to ``peer`` (the silo whose stale cache routed a
        message here). Best-effort — a lost notice only costs the peer
        forward hops until its entry's TTL expires."""
        try:
            self.silo.runtime_client.send_request(
                target_grain=GrainId.system_target(_dir_type_code(), peer),
                grain_class=DirectoryTarget,
                interface_name=DIRECTORY_TARGET,
                method_name="dir_cache_invalidate", args=(grain_id,),
                kwargs={}, is_one_way=True, target_silo=peer,
                category=Category.SYSTEM)
        except Exception:  # noqa: BLE001 — peer may be mid-death
            log.debug("cache-invalidate notice to %s failed", peer)

    async def unregister_after_nonexistent(self, grain_id: GrainId) -> None:
        """This silo received a message for ``grain_id`` but hosts no such
        activation: tell the directory owner to drop any registration
        pointing here (unless it names an activation that is in fact
        live — a re-creation racing this report keeps its entry)."""
        live = [a.activation_id
                for a in self.silo.catalog.by_grain.get(grain_id, [])]
        owner = self.ring.owner(grain_id.uniform_hash)
        me = self.silo.silo_address
        try:
            if owner is None or owner == me:
                cur = self.partition.get(grain_id)
                if cur is not None and cur.silo == me and \
                        cur.activation not in live:
                    self.partition.pop(grain_id, None)
                    self.cache.pop(grain_id, None)
            else:
                await self._target_ref(owner, "dir_drop_stale", grain_id,
                                       me, live)
        except Exception:  # noqa: BLE001 — best-effort heal; the next
            # miss reports again
            log.debug("stale-entry report failed for %s", grain_id)

    # ------------------------------------------------------------------
    # Owner-side partition ops
    # ------------------------------------------------------------------
    def local_lookup_or_place(self, grain_id: GrainId,
                              placement_name: str | None,
                              requester: SiloAddress,
                              interface_name: str | None = None,
                              requested_version: int = 0):
        reg = self.partition.get(grain_id)
        if reg is not None and reg.silo in self.alive_set:
            return reg.silo, False
        director = self.placement.director_by_name(placement_name)
        candidates = self._alive()
        if interface_name is not None:
            # version gate at addressing time (Dispatcher.cs:725-732).
            # Cross-process silos are covered by the exchanged type map
            # (TypeManager); a silo whose map has not arrived is simply
            # not a candidate — gating never silently passes.
            compat = self.versions.compatible_silos(
                interface_name, requested_version, candidates)
            if compat:
                candidates = compat
            elif any(s != self.silo.silo_address
                     and s not in self.versions.remote_maps
                     and getattr(self.silo.fabric, "silos", {}).get(s) is None
                     for s in candidates):
                # some candidate's type map hasn't arrived yet (startup /
                # join window): transient — the caller's resend retries
                # after the exchange lands, rather than failing hard
                from ..core.errors import TransientPlacementError
                raise TransientPlacementError(
                    f"type maps still exchanging for {interface_name}; "
                    "retry")
            else:
                from ..core.errors import OrleansError
                raise OrleansError(
                    f"no silo hosts a version of {interface_name} compatible "
                    f"with requested v{requested_version}")
        silo = director.place(grain_id, requester, candidates)
        return silo, True

    def local_register(self, address: ActivationAddress) -> ActivationAddress:
        """AddSingleActivation (GrainDirectoryPartition.cs:304): first
        registration wins; returns the winning address."""
        cur = self.partition.get(address.grain)
        if cur is not None and cur.silo in self.alive_set:
            return cur
        self.partition[address.grain] = address
        return address

    def local_migrate_register(self, address: ActivationAddress,
                               prev_activation) -> ActivationAddress:
        """Owner-side migrate re-registration: replaces the entry when it
        names the migrating activation (or is dead/absent); an unrelated
        LIVE registration wins instead — same first-wins discipline as
        AddSingleActivation, with the migrating activation's claim carried
        by ``prev_activation``. The owner's own cache entry is dropped so
        lookups it answers from cache never resurrect the old address."""
        cur = self.partition.get(address.grain)
        if cur is not None and cur.silo in self.alive_set and \
                cur.activation != prev_activation and \
                cur.activation != address.activation:
            return cur
        self.partition[address.grain] = address
        self.cache.pop(address.grain, None)
        return address

    def local_unregister(self, address: ActivationAddress) -> None:
        cur = self.partition.get(address.grain)
        if cur is not None and cur.activation == address.activation:
            self.partition.pop(address.grain, None)

    def _cache_put(self, grain_id: GrainId, silo: SiloAddress) -> None:
        self.cache.put(grain_id, silo)

    # ------------------------------------------------------------------
    # Adaptive-cache maintainer (AdaptiveDirectoryCacheMaintainer.cs:243)
    # ------------------------------------------------------------------
    def start_cache_maintainer(self) -> None:
        if self._maintainer_task is None and \
                self.silo.config.directory_cache_refresh_period > 0:
            self._maintainer_task = asyncio.get_running_loop().create_task(
                self._maintainer_loop())

    def stop_cache_maintainer(self) -> None:
        if self._maintainer_task is not None:
            self._maintainer_task.cancel()
            self._maintainer_task = None

    async def _maintainer_loop(self) -> None:
        period = self.silo.config.directory_cache_refresh_period
        while True:
            await asyncio.sleep(period)
            try:
                await self._refresh_hot_entries(period)
            except Exception:  # noqa: BLE001 — next sweep retries
                log.debug("directory cache refresh failed", exc_info=True)

    async def _refresh_hot_entries(self, horizon: float) -> None:
        """Refresh entries accessed since the last sweep that are expired
        or expiring within one period: batch per directory owner, fold
        answers back (same silo → TTL doubles; moved → reset; gone →
        drop). Hot routes stay fresh instead of paying staleness in
        forward hops."""
        gids = self.cache.sweep_candidates(horizon)
        if not gids:
            return
        me = self.silo.silo_address
        by_owner: dict[SiloAddress, list[GrainId]] = {}
        for gid in gids:
            owner = self.ring.owner(gid.uniform_hash)
            if owner is not None:
                by_owner.setdefault(owner, []).append(gid)
        for owner, batch in by_owner.items():
            if owner == me:
                results = await self.target.dir_lookup_many(batch)
            else:
                try:
                    results = await self._target_ref(
                        owner, "dir_lookup_many", batch)
                except Exception:  # noqa: BLE001 — owner mid-death: the
                    # membership sweep clears its range; skip this batch
                    continue
            for gid, silo in zip(batch, results, strict=True):
                self.cache.refresh_result(gid, silo)
            self.silo.stats.increment("directory.cache.refreshed",
                                      len(batch))

    # ------------------------------------------------------------------
    # Membership events (LocalGrainDirectory.cs:431-460 + handoff manager)
    # ------------------------------------------------------------------
    def on_membership_change(self, silos: list[SiloAddress],
                             dead: list[SiloAddress]) -> None:
        # Catalog.OnSiloStatusChange (Catalog.cs:175,1400 via the
        # directory callback, LocalGrainDirectory.cs:274-326): local
        # activations whose directory registration lived on a dead silo's
        # partition lost that registration with the partition — the next
        # remote call would mint a duplicate activation elsewhere and the
        # two would race on storage etags. Deactivate them first (checked
        # against the pre-update ring, which still maps the dead silo's
        # range); the next call re-creates and re-registers cleanly.
        if dead:
            dead_set = set(dead)
            catalog = self.silo.catalog
            for gid, acts in list(catalog.by_grain.items()):
                if gid.is_system_target():
                    continue
                reg_owner = self.ring.owner(gid.uniform_hash)
                if reg_owner in dead_set:
                    for act in list(acts):
                        # stateless workers are never directory-registered
                        # (catalog._init_activation skips them) — nothing
                        # of theirs died with the partition
                        if not act.is_stateless_worker:
                            catalog.schedule_deactivation(act)
        self.ring.update(silos)
        alive = set(silos)
        self.alive_set = alive
        self.alive_list = self.ring.silos
        # type-map exchange bookkeeping (TypeManager refresh on change)
        for d in dead:
            self.versions.forget(d)
        for s in silos:
            if s != self.silo.silo_address and \
                    s not in self.versions.remote_maps:
                self.versions.schedule_fetch(s)
        # drop directory entries for activations on dead silos: the next
        # call re-creates the grain elsewhere (virtual-actor guarantee)
        for gid, addr in list(self.partition.items()):
            if addr.silo not in alive:
                self.partition.pop(gid, None)
        for gid, silo in list(self.cache.items()):
            if silo not in alive:
                self.cache.pop(gid, None)
        # re-range: replicate entries we no longer own to the new owner.
        # The entry is popped only after the new owner acks — during the
        # transfer window both silos answer lookups consistently (the old
        # owner still holds the registration); failed pushes keep the entry
        # here for retry at the next membership change.
        moved: dict[SiloAddress, list] = {}
        for gid, addr in self.partition.items():
            owner = self.ring.owner(gid.uniform_hash)
            if owner is not None and owner != self.silo.silo_address:
                moved.setdefault(owner, []).append((gid, addr))
        for owner, entries in moved.items():
            asyncio.ensure_future(self._handoff_entries(owner, entries))

    async def _handoff_entries(self, owner: SiloAddress, entries: list) -> None:
        try:
            await self._target_ref(owner, "dir_handoff",
                                   [addr for _, addr in entries])
        except Exception:  # noqa: BLE001 — keep entries; retried on next change
            log.debug("re-range handoff to %s failed; entries retained", owner)
            return
        for gid, addr in entries:
            cur = self.partition.get(gid)
            if cur is not None and cur.activation == addr.activation:
                self.partition.pop(gid, None)

    async def handoff_all(self) -> None:
        """Graceful-stop handoff: push the whole partition to successors
        (GrainDirectoryHandoffManager on ShuttingDown). Without this,
        registrations for grains hosted on OTHER silos die with this
        partition and single-activation breaks (duplicate activations)."""
        others = [s for s in self._alive() if s != self.silo.silo_address]
        if not others:
            return
        ring = ConsistentRing(others)
        moved: dict[SiloAddress, list[ActivationAddress]] = {}
        for gid, addr in self.partition.items():
            if addr.silo == self.silo.silo_address:
                continue  # our activations die with us
            owner = ring.owner(gid.uniform_hash)
            if owner is not None:
                moved.setdefault(owner, []).append(addr)
        for owner, entries in moved.items():
            try:
                await self._target_ref(owner, "dir_handoff", entries)
            except Exception:  # noqa: BLE001
                log.debug("handoff to %s failed", owner)
        self.partition.clear()


async def _swallow(fut):
    try:
        await fut
    except Exception:  # noqa: BLE001
        pass


def _dir_type_code() -> int:
    from ..core.ids import type_code_of
    return type_code_of(DIRECTORY_TARGET)
