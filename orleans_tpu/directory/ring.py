"""Consistent-hash rings (reference L5 support).

Re-design of /root/reference/src/Orleans.Runtime/ConsistentRing/:
``ConsistentRingProvider.cs:17`` (one point per silo — directory ownership),
``VirtualBucketsRingProvider.cs:15,29`` (N virtual buckets per silo —
reminder ranges), plus ``RingRange`` (Core/Runtime/RingRange.cs).

Hash space is the 63-bit non-negative range of ``stable_hash64``.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass
from typing import Iterable

from ..core.ids import SiloAddress, stable_hash64

__all__ = ["ConsistentRing", "VirtualBucketRing", "EquallyDividedRing",
           "RingRange"]

HASH_SPACE = 1 << 63


@dataclass(frozen=True)
class RingRange:
    """Half-open arc (begin, end] on the ring; wraps modulo HASH_SPACE."""

    begin: int
    end: int

    def contains(self, point: int) -> bool:
        if self.begin == self.end:
            return True  # full ring (single owner)
        if self.begin < self.end:
            return self.begin < point <= self.end
        return point > self.begin or point <= self.end

    @property
    def size(self) -> int:
        return (self.end - self.begin) % HASH_SPACE or HASH_SPACE


class ConsistentRing:
    """One point per silo (ConsistentRingProvider.cs): the owner of a key is
    the first silo clockwise from the key's hash."""

    def __init__(self, silos: Iterable[SiloAddress] = ()):
        self._points: list[tuple[int, SiloAddress]] = []
        for s in silos:
            self.add(s)

    def add(self, silo: SiloAddress) -> None:
        point = silo.uniform_hash
        entry = (point, silo)
        if entry not in self._points:
            bisect.insort(self._points, entry)

    def remove(self, silo: SiloAddress) -> None:
        self._points = [(p, s) for (p, s) in self._points if s != silo]

    def update(self, silos: Iterable[SiloAddress]) -> None:
        self._points = sorted((s.uniform_hash, s) for s in set(silos))

    @property
    def silos(self) -> list[SiloAddress]:
        return [s for _, s in self._points]

    def owner(self, key_hash: int) -> SiloAddress | None:
        """CalculateTargetSilo (LocalGrainDirectory.cs:477-546)."""
        if not self._points:
            return None
        i = bisect.bisect_left(self._points, (key_hash % HASH_SPACE,))
        if i == len(self._points):
            i = 0
        return self._points[i][1]

    def my_range(self, silo: SiloAddress) -> RingRange | None:
        """The arc this silo owns: (predecessor, me]."""
        if not self._points:
            return None
        idx = None
        for i, (_, s) in enumerate(self._points):
            if s == silo:
                idx = i
                break
        if idx is None:
            return None
        me = self._points[idx][0]
        pred = self._points[idx - 1][0]  # wraps via [-1]
        return RingRange(pred, me)

    def successors(self, silo: SiloAddress, k: int) -> list[SiloAddress]:
        """k distinct silos clockwise after ``silo`` (probe targets,
        MembershipOracle.cs:741-776)."""
        others = [s for _, s in self._points if s != silo]
        if not others:
            return []
        all_pts = [s for _, s in self._points]
        try:
            i = all_pts.index(silo)
        except ValueError:
            return others[:k]
        ordered = all_pts[i + 1:] + all_pts[:i]
        return [s for s in ordered if s != silo][:k]


class VirtualBucketRing:
    """N virtual points per silo (VirtualBucketsRingProvider.cs:15,29):
    smooths range sizes for reminder partitioning."""

    def __init__(self, buckets_per_silo: int = 30):
        self.buckets_per_silo = buckets_per_silo
        self._points: list[tuple[int, SiloAddress]] = []

    def update(self, silos: Iterable[SiloAddress]) -> None:
        pts = []
        for s in set(silos):
            for b in range(self.buckets_per_silo):
                pts.append((stable_hash64(f"vb|{s.endpoint}|{s.generation}|{b}"), s))
        self._points = sorted(pts)

    def owner(self, key_hash: int) -> SiloAddress | None:
        if not self._points:
            return None
        i = bisect.bisect_left(self._points, (key_hash % HASH_SPACE,))
        if i == len(self._points):
            i = 0
        return self._points[i][1]

    def ranges_of(self, silo: SiloAddress) -> list[RingRange]:
        """All arcs owned by ``silo`` (reminder load ranges)."""
        if not self._points:
            return []
        out = []
        for i, (pt, s) in enumerate(self._points):
            if s == silo:
                pred = self._points[i - 1][0]
                out.append(RingRange(pred, pt))
        return out

    def owns(self, silo: SiloAddress, key_hash: int) -> bool:
        return self.owner(key_hash) == silo


class EquallyDividedRing:
    """Exact 1/N split of the hash space over the sorted alive set
    (EquallyDividedRangeRingProvider.cs:10): deterministic equal ranges —
    used by grain services that want uniform load rather than
    hash-positioned arcs. Ranges are derived, not point-based: silo i of N
    (sorted by address) owns [i*SPACE/N, (i+1)*SPACE/N)."""

    def __init__(self, silos: Iterable[SiloAddress] = ()):
        self._silos: list[SiloAddress] = []
        self.update(silos)

    def update(self, silos: Iterable[SiloAddress]) -> None:
        self._silos = sorted(set(silos),
                             key=lambda s: (s.endpoint, s.generation))

    @property
    def silos(self) -> list[SiloAddress]:
        return list(self._silos)

    def _bounds(self, i: int) -> tuple[int, int]:
        n = len(self._silos)
        return (HASH_SPACE * i) // n, (HASH_SPACE * (i + 1)) // n

    def owner(self, key_hash: int) -> SiloAddress | None:
        n = len(self._silos)
        if not n:
            return None
        k = key_hash % HASH_SPACE
        # invert the exact integer split: candidate index then adjust
        i = min((k * n) // HASH_SPACE, n - 1)
        lo, hi = self._bounds(i)
        if k < lo:
            i -= 1
        elif k >= hi:
            i += 1
        return self._silos[i]

    def my_range(self, silo: SiloAddress) -> RingRange | None:
        try:
            i = self._silos.index(silo)
        except ValueError:
            return None
        lo, hi = self._bounds(i)
        # RingRange is (begin, end]: shift the half-open [lo, hi) by -1
        return RingRange((lo - 1) % HASH_SPACE, (hi - 1) % HASH_SPACE)
