"""Distributed grain directory + consistent rings (reference L5)."""

from .locator import DistributedLocator  # noqa: F401
from .ring import ConsistentRing, RingRange, VirtualBucketRing  # noqa: F401
