"""Distributed grain directory + consistent rings (reference L5)."""

from .locator import DistributedLocator  # noqa: F401
from .ring import (  # noqa: F401
    ConsistentRing,
    EquallyDividedRing,
    RingRange,
    VirtualBucketRing,
)
