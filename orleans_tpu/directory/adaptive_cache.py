"""Adaptive grain-directory cache: per-entry TTLs that adapt on
hit/invalidation, plus a maintainer that refreshes hot entries.

Re-design of /root/reference/src/Orleans.Runtime/GrainDirectory/
``AdaptiveGrainDirectoryCache.cs:178`` (entries carry a TTL that DOUBLES
each time a lookup re-validates the same answer and resets when the entry
proves wrong) and ``AdaptiveDirectoryCacheMaintainer.cs:243`` (a periodic
sweep batches owner lookups for recently-accessed entries so hot routes
stay fresh instead of paying staleness in forward hops).

Departures: eviction is LRU-bounded like the rest of the repo's caches
(the reference's maintainer also drops untouched entries; LRU subsumes
that), and the maintainer refreshes entries that were ACCESSED since the
last sweep and are expired or expiring within one period — cold entries
cost nothing until traffic returns."""

from __future__ import annotations

import collections
import time
from typing import Any, Callable

__all__ = ["AdaptiveDirectoryCache"]


class _Entry:
    __slots__ = ("silo", "ttl", "expires")

    def __init__(self, silo, ttl: float, now: float):
        self.silo = silo
        self.ttl = ttl
        self.expires = now + ttl


class AdaptiveDirectoryCache:
    """Bounded LRU of grain → silo with adaptive per-entry TTLs.

    API shape matches how the locator used its plain OrderedDict
    (get/pop/items/len) so it drops in; ``put`` and ``sweep`` carry the
    adaptive behavior."""

    def __init__(self, size: int, initial_ttl: float = 5.0,
                 max_ttl: float = 120.0,
                 clock: Callable[[], float] = time.monotonic):
        self.size = size
        self.initial_ttl = initial_ttl
        self.max_ttl = max_ttl
        self.clock = clock
        self._d: collections.OrderedDict[Any, _Entry] = \
            collections.OrderedDict()
        # gids touched since the last sweep: the maintainer iterates THIS
        # (O(recent traffic)), never the full cache (O(cache_size) per
        # period would burn the single-core event loop while idle)
        self._accessed: set = set()
        self.hits = 0
        self.expired_hits = 0

    def __len__(self) -> int:
        return len(self._d)

    def __contains__(self, gid) -> bool:
        return gid in self._d

    def get(self, gid):
        """The cached silo, or None when absent OR past its TTL (an
        expired entry reads as a miss — the caller re-resolves and put()
        re-arms it — but stays resident so the maintainer sees it was
        wanted)."""
        e = self._d.get(gid)
        if e is None:
            return None
        # bounded even when no maintainer drains it (refresh period 0):
        # distinct-gid traffic must not grow the set past the cache
        # itself. Clear only when a NEW gid would exceed the bound — a
        # steady-state working set of exactly `size` hot gids must keep
        # its marks or the maintainer would never see them at sweep time
        if gid not in self._accessed and len(self._accessed) >= self.size:
            self._accessed.clear()
        self._accessed.add(gid)
        if self.clock() >= e.expires:
            self.expired_hits += 1
            return None
        self.hits += 1
        self._d.move_to_end(gid)
        return e.silo

    def put(self, gid, silo) -> None:
        """Adaptive arm: re-confirming the SAME answer doubles the TTL
        (up to max); a new/changed answer starts at the initial TTL —
        exactly the reference's AddOrUpdate semantics."""
        now = self.clock()
        e = self._d.get(gid)
        if e is not None and e.silo == silo:
            e.ttl = min(e.ttl * 2, self.max_ttl)
            e.expires = now + e.ttl
        else:
            self._d[gid] = _Entry(silo, self.initial_ttl, now)
        self._d.move_to_end(gid)
        while len(self._d) > self.size:
            self._d.popitem(last=False)

    def valid_silo(self, gid):
        """TTL-checked entry WITHOUT the hit/access/LRU bookkeeping — the
        dispatcher's catalog-first guard calls this per message, and the
        expired→miss contract is what bounds a usurped duplicate to one
        TTL (the fall-through slow path re-resolves and re-arms); the
        bookkeeping belongs to the resolution path, not the guard."""
        e = self._d.get(gid)
        if e is None or self.clock() >= e.expires:
            return None
        return e.silo

    def pop(self, gid, default=None):
        e = self._d.pop(gid, None)
        return default if e is None else e.silo

    def items(self):
        return [(gid, e.silo) for gid, e in self._d.items()]

    # -- maintainer support ------------------------------------------------
    def sweep_candidates(self, horizon: float) -> list:
        """Entries touched since the last sweep that are expired or will
        expire within ``horizon`` seconds — the refresh set. Consumes the
        accessed marks (each sweep sees only NEW traffic)."""
        now = self.clock()
        touched, self._accessed = self._accessed, set()
        out = []
        for gid in touched:
            e = self._d.get(gid)
            if e is not None and e.expires <= now + horizon:
                out.append(gid)
        return out

    def refresh_result(self, gid, silo) -> None:
        """Fold one owner answer from the maintainer: same silo → TTL
        doubles; different silo → replace at initial TTL; None (no
        registration — the grain deactivated) → drop."""
        if silo is None:
            self._d.pop(gid, None)
        else:
            self.put(gid, silo)
