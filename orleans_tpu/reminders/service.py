"""Local reminder service: ticks the durable reminders this silo owns.

Re-design of /root/reference/src/Orleans.Runtime/ReminderService/
LocalReminderService.cs:12 (RegisterOrUpdateReminder:81, per-reminder timers,
range-based load + re-read on ring change) over the virtual-bucket ring
(VirtualBucketsRingProvider.cs:15,29). Start is gated on membership the same
way the reference gates on ring stability (Silo.cs:534-546): the service
(re)computes its owned ranges from the locator's alive view and subscribes
to the membership oracle when one is installed.

A reminder tick is an ordinary grain call to ``receive_reminder(name,
status)`` (IRemindable.ReceiveReminder) — the grain re-activates anywhere in
the cluster if needed, which is exactly how reminders survive deactivation
and silo death.
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
from dataclasses import dataclass
from typing import TYPE_CHECKING

from ..core.errors import ReminderError
from ..core.ids import GrainId, type_code_of
from ..core.message import Category
from ..directory.ring import VirtualBucketRing
from .table import ReminderEntry, ReminderTable

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.reminders")

REMINDER_TARGET = "ReminderTarget"

__all__ = ["TickStatus", "LocalReminderService", "ReminderHandle",
           "add_reminders"]


@dataclass(frozen=True)
class TickStatus:
    """Passed to receive_reminder (TickStatus in the reference API)."""

    first_tick_time: float
    period: float
    current_tick_time: float


@dataclass(frozen=True)
class ReminderHandle:
    """Opaque registration token returned to grains (IGrainReminder)."""

    grain_id: GrainId
    name: str
    etag: int


class ReminderTarget:
    """Per-silo system target: remote refresh hints from peers that just
    wrote a table row owned by this silo."""

    _activation = None

    def __init__(self, service: "LocalReminderService"):
        self.service = service

    async def rem_refresh(self) -> None:
        self.service.schedule_refresh()


class _ReminderTimer:
    """One ticking reminder (the per-entry timer inside the local range)."""

    def __init__(self, service: "LocalReminderService", entry: ReminderEntry):
        self.service = service
        self.entry = entry
        self.task = asyncio.get_running_loop().create_task(self._run())

    def stop(self) -> None:
        self.task.cancel()

    async def _run(self) -> None:
        e = self.entry
        while True:
            now = time.time()
            if now < e.start_at:
                fire_at = e.start_at
            else:
                k = math.floor((now - e.start_at) / e.period) + 1
                fire_at = e.start_at + k * e.period
            await asyncio.sleep(max(0.0, fire_at - time.time()))
            status = TickStatus(e.start_at, e.period, fire_at)
            try:
                await self.service.deliver_tick(e, status)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — log and keep the schedule
                log.exception("reminder %s tick failed for %s",
                              e.name, e.grain_id)


class LocalReminderService:
    """One per silo; installed as ``silo.reminders``."""

    def __init__(self, silo: "Silo", table: ReminderTable,
                 buckets_per_silo: int = 30, refresh_period: float = 5.0):
        self.silo = silo
        self.table = table
        self.ring = VirtualBucketRing(buckets_per_silo)
        self.refresh_period = refresh_period
        self.local: dict[tuple[GrainId, str], _ReminderTimer] = {}
        # (grain_id, name) -> the registering turn's (trace_id, span_id):
        # span-link arming context for tick-rooted traces (bounded by the
        # table rows this silo ever registered; popped on unregister)
        self._arm_links: dict[tuple[GrainId, str], tuple] = {}
        self.target = ReminderTarget(self)
        silo.register_system_target(self.target, REMINDER_TARGET)
        self._refresh_wanted = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._stopped = False

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        if self.silo.membership is not None:
            self.silo.membership.subscribe(
                lambda alive, dead: self.schedule_refresh())
        self._task = asyncio.get_running_loop().create_task(self._loop())
        self.schedule_refresh()

    def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            self._task = None
        for t in self.local.values():
            t.stop()
        self.local.clear()

    def schedule_refresh(self) -> None:
        self._refresh_wanted.set()

    async def _loop(self) -> None:
        while not self._stopped:
            try:
                await asyncio.wait_for(self._refresh_wanted.wait(),
                                       timeout=self.refresh_period)
            except asyncio.TimeoutError:
                pass
            self._refresh_wanted.clear()
            try:
                await self._refresh()
            except Exception:  # noqa: BLE001
                log.exception("reminder range refresh failed")

    async def _refresh(self) -> None:
        """Reload the rows in my ranges; start/stop/restart local timers
        (the read-my-range + re-read-on-range-change behavior)."""
        self.ring.update(self.silo.locator.alive_list)
        me = self.silo.silo_address
        rows = await self.table.read_all()
        mine = {(e.grain_id, e.name): e for e in rows
                if self.ring.owns(me, e.grain_id.uniform_hash)}
        for key, timer in list(self.local.items()):
            cur = mine.get(key)
            if cur is None or cur.etag != timer.entry.etag:
                timer.stop()
                del self.local[key]
        for key, entry in mine.items():
            if key not in self.local:
                self.local[key] = _ReminderTimer(self, entry)

    # -- grain-facing API (Grain.register_reminder et al.) ----------------
    async def register_or_update(self, grain_id: GrainId, name: str,
                                 due: float, period: float) -> ReminderHandle:
        if period < 0.05:
            raise ReminderError(
                f"reminder period {period}s below minimum (reference floor "
                "is 1 minute; scaled-down floor here is 50ms)")
        iface = self._interface_of(grain_id)
        entry = ReminderEntry(
            grain_id=grain_id, interface_name=iface, name=name,
            start_at=time.time() + due, period=period)
        from ..observability.tracing import current_trace
        link = current_trace.get()
        if link is not None:
            # arming context for span links: tick-rooted traces on THIS
            # silo link back to the registering turn's trace. Best-effort
            # and silo-local by design — the link does not ride the table
            # row, so a tick fired by a different owner roots unlinked.
            self._arm_links[(grain_id, name)] = link
        etag = await self.table.upsert_row(entry)
        await self._notify_owner(grain_id)
        return ReminderHandle(grain_id, name, etag)

    async def unregister(self, grain_id: GrainId, name: str) -> None:
        removed = await self.table.remove_row(grain_id, name)
        self._arm_links.pop((grain_id, name), None)
        if not removed:
            raise ReminderError(f"no reminder {name!r} for {grain_id}")
        await self._notify_owner(grain_id)

    async def get(self, grain_id: GrainId, name: str) -> ReminderHandle | None:
        e = await self.table.read_row(grain_id, name)
        return ReminderHandle(grain_id, name, e.etag) if e else None

    async def list(self, grain_id: GrainId) -> list[ReminderHandle]:
        rows = await self.table.read_grain_rows(grain_id)
        return [ReminderHandle(grain_id, e.name, e.etag) for e in rows]

    # -- internals -------------------------------------------------------
    def _interface_of(self, grain_id: GrainId) -> str:
        for cls in self.silo.registry.all_classes():
            if type_code_of(cls.__name__) == grain_id.type_code:
                return cls.__name__
        raise ReminderError(
            f"no registered grain class for type code {grain_id.type_code}")

    async def _notify_owner(self, grain_id: GrainId) -> None:
        """Kick the owning silo's service so the new row ticks promptly
        (instead of waiting out a refresh period)."""
        self.ring.update(self.silo.locator.alive_list)
        owner = self.ring.owner(grain_id.uniform_hash)
        if owner is None or owner == self.silo.silo_address:
            self.schedule_refresh()
            return
        gid = GrainId.system_target(type_code_of(REMINDER_TARGET), owner)
        try:
            self.silo.runtime_client.send_request(
                target_grain=gid, grain_class=ReminderTarget,
                interface_name=REMINDER_TARGET, method_name="rem_refresh",
                args=(), kwargs={}, is_one_way=True, target_silo=owner,
                category=Category.SYSTEM)
        except Exception:  # noqa: BLE001 — periodic refresh is the backstop
            log.debug("reminder owner notify to %s failed", owner)

    async def deliver_tick(self, entry: ReminderEntry,
                           status: TickStatus) -> None:
        """One tick = one ordinary grain call (IRemindable.ReceiveReminder)."""
        cls = self.silo.registry.resolve(entry.interface_name)
        if cls is None:
            log.warning("reminder %s: grain class %s not registered here",
                        entry.name, entry.interface_name)
            return
        self.silo.stats.increment("reminders.ticks")
        from ..observability.tracing import arm_root_link
        # tick turns root fresh traces; carry the registering turn's
        # context as a span link on the new root (set each tick — the
        # timer task's context persists, and an unlinked entry must
        # clear a predecessor's link)
        arm_root_link(self._arm_links.get((entry.grain_id, entry.name)))
        fut = self.silo.runtime_client.send_request(
            target_grain=entry.grain_id, grain_class=cls,
            interface_name=entry.interface_name,
            method_name="receive_reminder",
            args=(entry.name, status), kwargs={})
        await fut


def add_reminders(silo: "Silo", table: ReminderTable,
                  **kw) -> LocalReminderService:
    """Install the reminder service on a silo pre-start (Silo.cs:534-546)."""
    service = LocalReminderService(silo, table, **kw)
    silo.reminders = service
    from ..runtime.silo import ServiceLifecycleStage
    silo.subscribe_lifecycle(ServiceLifecycleStage.RUNTIME_GRAIN_SERVICES,
                             service.start, service.stop)
    return service
