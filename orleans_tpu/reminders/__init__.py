"""Durable reminders over the virtual-bucket ring (reference L11,
src/Orleans.Runtime/ReminderService/)."""

from .service import (
    LocalReminderService,
    ReminderHandle,
    TickStatus,
    add_reminders,
)
from .table import (
    InMemoryReminderTable,
    ReminderEntry,
    ReminderTable,
    SqliteReminderTable,
)

__all__ = [
    "LocalReminderService", "ReminderHandle", "TickStatus", "add_reminders",
    "ReminderTable", "InMemoryReminderTable", "SqliteReminderTable",
    "ReminderEntry",
]
