"""Reminder table: durable schedule rows shared by the cluster.

Re-design of /root/reference/src/Orleans.Core/SystemTargetInterfaces/
IReminderTable.cs (ReminderEntry/ReminderTableData) and its backends:
InMemoryRemindersTable / MockReminderTable (ReminderService/) for dev-test,
and the SQL pack (src/AdoNet/Orleans.Reminders.AdoNet) → sqlite here.

Rows are keyed (grain, reminder name) and carry an etag for CAS removal;
range reads key off the grain's 64-bit uniform hash (the virtual-bucket
ring partitioning input, VirtualBucketsRingProvider.cs:15).
"""

from __future__ import annotations

import asyncio
import json
import sqlite3
from dataclasses import dataclass, replace

from ..core.ids import GrainCategory, GrainId

__all__ = ["ReminderEntry", "ReminderTable", "InMemoryReminderTable",
           "SqliteReminderTable"]


@dataclass
class ReminderEntry:
    """One durable reminder registration."""

    grain_id: GrainId
    interface_name: str
    name: str
    start_at: float   # unix time of first tick
    period: float     # seconds between ticks
    etag: int = 0

    def copy(self) -> "ReminderEntry":
        return replace(self)

    def to_json(self) -> dict:
        g = self.grain_id
        key = g.key.hex() if isinstance(g.key, bytes) else g.key
        return {
            "cat": int(g.category), "tc": g.type_code, "key": key,
            "kb": isinstance(g.key, bytes), "ext": g.key_ext,
            "iface": self.interface_name, "name": self.name,
            "start": self.start_at, "period": self.period, "etag": self.etag,
        }

    @classmethod
    def from_json(cls, d: dict) -> "ReminderEntry":
        key = bytes.fromhex(d["key"]) if d["kb"] else d["key"]
        gid = GrainId(GrainCategory(d["cat"]), d["tc"], key, d["ext"])
        return cls(gid, d["iface"], d["name"], d["start"], d["period"],
                   d["etag"])


class ReminderTable:
    """Abstract reminder store (IReminderTable)."""

    async def read_all(self) -> list[ReminderEntry]:
        raise NotImplementedError

    async def read_row(self, grain_id: GrainId,
                       name: str) -> ReminderEntry | None:
        raise NotImplementedError

    async def read_grain_rows(self, grain_id: GrainId) -> list[ReminderEntry]:
        raise NotImplementedError

    async def upsert_row(self, entry: ReminderEntry) -> int:
        """Write/overwrite; returns the new etag."""
        raise NotImplementedError

    async def remove_row(self, grain_id: GrainId, name: str,
                         etag: int | None = None) -> bool:
        raise NotImplementedError

    async def delete_table(self) -> None:
        raise NotImplementedError


class InMemoryReminderTable(ReminderTable):
    """Dev/test backend (InMemoryRemindersTable)."""

    def __init__(self) -> None:
        self._rows: dict[tuple[GrainId, str], ReminderEntry] = {}
        self._etag = 0
        self._lock = asyncio.Lock()

    async def read_all(self):
        async with self._lock:
            return [e.copy() for e in self._rows.values()]

    async def read_row(self, grain_id, name):
        async with self._lock:
            e = self._rows.get((grain_id, name))
            return e.copy() if e else None

    async def read_grain_rows(self, grain_id):
        async with self._lock:
            return [e.copy() for (g, _), e in self._rows.items()
                    if g == grain_id]

    async def upsert_row(self, entry):
        async with self._lock:
            self._etag += 1
            entry = entry.copy()
            entry.etag = self._etag
            self._rows[(entry.grain_id, entry.name)] = entry
            return entry.etag

    async def remove_row(self, grain_id, name, etag=None):
        async with self._lock:
            cur = self._rows.get((grain_id, name))
            if cur is None or (etag is not None and cur.etag != etag):
                return False
            del self._rows[(grain_id, name)]
            return True

    async def delete_table(self):
        async with self._lock:
            self._rows.clear()


class SqliteReminderTable(ReminderTable):
    """SQL backend (the AdoNet reminders analog); ``:memory:`` for tests."""

    def __init__(self, path: str) -> None:
        self._db = sqlite3.connect(path)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS reminders ("
            " gkey TEXT NOT NULL, name TEXT NOT NULL, entry TEXT NOT NULL,"
            " etag INTEGER NOT NULL, PRIMARY KEY (gkey, name))")
        self._db.commit()
        self._lock = asyncio.Lock()
        self._etag = 0

    @staticmethod
    def _gkey(grain_id: GrainId) -> str:
        return str(grain_id)

    async def read_all(self):
        async with self._lock:
            rows = self._db.execute("SELECT entry FROM reminders").fetchall()
            return [ReminderEntry.from_json(json.loads(r[0])) for r in rows]

    async def read_row(self, grain_id, name):
        async with self._lock:
            r = self._db.execute(
                "SELECT entry FROM reminders WHERE gkey=? AND name=?",
                (self._gkey(grain_id), name)).fetchone()
            return ReminderEntry.from_json(json.loads(r[0])) if r else None

    async def read_grain_rows(self, grain_id):
        async with self._lock:
            rows = self._db.execute(
                "SELECT entry FROM reminders WHERE gkey=?",
                (self._gkey(grain_id),)).fetchall()
            return [ReminderEntry.from_json(json.loads(r[0])) for r in rows]

    async def upsert_row(self, entry):
        async with self._lock:
            self._etag = self._etag + 1
            entry = entry.copy()
            entry.etag = self._etag
            self._db.execute(
                "INSERT INTO reminders (gkey, name, entry, etag)"
                " VALUES (?,?,?,?)"
                " ON CONFLICT (gkey, name) DO UPDATE SET entry=excluded.entry,"
                " etag=excluded.etag",
                (self._gkey(entry.grain_id), entry.name,
                 json.dumps(entry.to_json()), entry.etag))
            self._db.commit()
            return entry.etag

    async def remove_row(self, grain_id, name, etag=None):
        async with self._lock:
            if etag is None:
                cur = self._db.execute(
                    "DELETE FROM reminders WHERE gkey=? AND name=?",
                    (self._gkey(grain_id), name))
            else:
                cur = self._db.execute(
                    "DELETE FROM reminders WHERE gkey=? AND name=? AND etag=?",
                    (self._gkey(grain_id), name, etag))
            self._db.commit()
            return cur.rowcount == 1

    async def delete_table(self):
        async with self._lock:
            self._db.execute("DELETE FROM reminders")
            self._db.commit()
