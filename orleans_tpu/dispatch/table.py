"""ShardedActorTable: device-resident activation state, sharded over the mesh.

The fusion of the reference's ``ActivationDirectory`` (local activation map,
ActivationDirectory.cs) and ``GrainDirectoryPartition`` (consistent-hash
ownership, GrainDirectoryPartition.cs:207) re-expressed as device arrays
(SURVEY.md §7): activation state for one VectorGrain class lives in a slot
pool of shape ``[n_shards, capacity+1, *field]`` sharded over the ``silo``
mesh axis. Slot ``capacity`` (the last row) is a write sink for padding
lanes, so masked scatters never collide with real rows.

Key → shard is ``uniform_hash % n_shards`` (the ring's CalculateTargetSilo,
LocalGrainDirectory.cs:477, degenerated to a static mesh mapping); slot
within the shard comes from a host-side free list (the dynamic-activation-
table hard part: slot pool + free list, SURVEY.md §7 hard parts #2).

Two key regimes:
* **hashed** (general): host dict key→(shard, slot); per-key alloc/free.
* **dense** (bulk workloads, e.g. 1M Presence players with keys 0..N-1):
  ``ensure_dense(n)`` pre-provisions key i → (i % n_shards, i // n_shards)
  so bulk batches compute slots with vectorized integer math — no per-key
  Python. This is the 1M-msgs/sec path.
"""

from __future__ import annotations

import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import SILO_AXIS, make_mesh, shard_spec
from .vector_grain import VectorGrain, vector_methods

# directory-value encoding stride: loc = shard * _LOC_STRIDE + slot.
# Fixed (not the live capacity) so encoded values survive table growth;
# bounds per-shard capacity at 2^20 slots and shards at 2^10 within int32.
_LOC_STRIDE = 1 << 20

__all__ = ["ShardedActorTable"]


@partial(jax.jit, donate_argnums=0)
def _accumulate_hits(hits, slots_b, valid_b, scale):
    """Per-slot invocation counters, accumulated ON DEVICE as part of the
    dispatch tick (the hot-spot telemetry feed of orleans_tpu.rebalance):
    one masked scatter-add per tick — padding lanes address the sink row,
    so no host sync and no data-dependent shapes."""
    n = hits.shape[0]
    shard = jnp.arange(n, dtype=jnp.int32)[:, None]
    return hits.at[shard, slots_b].add(
        valid_b.astype(jnp.int32) * scale)


@jax.jit
def _move_state_rows(state, src_shard, src_slot, dst_shard, dst_slot):
    """Copy state rows (src_shard[i], src_slot[i]) → (dst_shard[i],
    dst_slot[i]) across every field — the device half of a live
    shard-to-shard migration. Purely functional (NO donation): the caller
    keeps the old arrays as the implicit rollback snapshot until the swap
    commits."""
    def one(arr):
        rows = arr[src_shard, src_slot]
        return arr.at[dst_shard, dst_slot].set(rows)
    return jax.tree_util.tree_map(one, state)


class ShardedActorTable:
    def __init__(self, grain_class: type[VectorGrain], mesh=None,
                 capacity_per_shard: int = 1024):
        self.grain_class = grain_class
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_shards = self.mesh.devices.size
        # power-of-two capacity: bounds distinct kernel shapes (grow() keeps
        # this invariant) and lets padded batch buckets (_bucket, also po2)
        # slice the slot pool contiguously in the dense fast path
        self.capacity = 1 << (int(capacity_per_shard) - 1).bit_length()
        self.methods = vector_methods(grain_class)
        # On a 1-device mesh, committed NamedSharding buffers pay a large
        # dispatch/layout penalty through the axon tunnel for zero benefit;
        # plain uncommitted arrays behave identically there.
        self.sharding = shard_spec(self.mesh) if self.n_shards > 1 else None
        # tick-serialization fence: a reentrant lock the off-loop tick
        # worker holds for every batch. State mutators/materializers
        # below (grow, move_rows, snapshot/restore, read_row) take it so
        # they never observe — or clobber — tbl.state while a worker-side
        # kernel has it donated mid-flight. Always present (uncontended
        # acquire is ~100ns on these cold paths, so standalone tables
        # just pay a no-op); VectorRuntime.register replaces it with the
        # owning engine's lock so every table in one engine shares the
        # worker's fence.
        self.fence = threading.RLock()

        # host bookkeeping
        self.key_to_slot: dict[int, tuple[int, int]] = {}  # key_hash → (shard, slot)
        # device-queryable mirror of the hashed-key directory: full 62-bit
        # key identity, value = shard * (capacity+1) ... encoded lazily per
        # lookup as shard/slot below. Lets sparse keys ride the on-device
        # routing path (route/apply_received sparse mode) — the on-chip
        # directory tier (ops.hash_probe; AdaptiveGrainDirectoryCache.cs:178)
        from ..ops.hash_probe import DeviceDirectory64
        self.device_dir = DeviceDirectory64()
        # key_hash → the GrainId uniform hash that ROUTES it (differs for
        # small-int keys, where key_hash is the key itself): ring-ownership
        # sweeps need the routing hash to decide who owns a resident row
        self.route_hash: dict[int, int] = {}
        self.free: list[list[int]] = [
            list(range(self.capacity - 1, -1, -1)) for _ in range(self.n_shards)]
        self.dense_n = 0  # keys [0, dense_n) are dense-mapped
        self.dense_per_shard = 0
        self.dense_active = np.zeros(0, dtype=bool)

        # device state: [n_shards, capacity+1, *shape]; row `capacity` is the
        # padding write sink
        self.state: dict[str, jax.Array] = {}
        for name, (dtype, shape) in grain_class.STATE.items():
            self.state[name] = self._put(
                jnp.zeros((self.n_shards, self.capacity + 1, *shape),
                          dtype=dtype))
        # hot-spot telemetry: per-slot invocation counters, [n_shards,
        # capacity+1] with the sink row absorbing padding lanes. Off by
        # default (an extra scatter-add per tick is pure overhead unless a
        # rebalancer consumes it) — see enable_hit_tracking.
        self.hits: jax.Array | None = None
        # cost attribution (observability.ledger, ISSUE 17): per-slot
        # accumulated tick cost in MICROSECONDS, same [n_shards,
        # capacity+1] layout / sink-row / donation / fence discipline as
        # the hit counters (int32 µs holds ~35 minutes of charged wall
        # per slot between reset_cost readouts). Off by default — see
        # enable_cost_tracking.
        self.cost: jax.Array | None = None

    # ------------------------------------------------------------------
    def _put(self, arr):
        """Commit to the mesh sharding (no-op on a 1-device mesh)."""
        return jax.device_put(arr, self.sharding) if self.sharding else arr

    def _put_rounds(self, arr):
        """Commit a [K, n_shards, ...] stacked-rounds array: sharded on the
        shard axis (dim 1), replicated over rounds."""
        if not self.sharding:
            return arr
        from jax.sharding import NamedSharding, PartitionSpec
        return jax.device_put(
            arr, NamedSharding(self.mesh, PartitionSpec(None, SILO_AXIS)))

    @property
    def sink_slot(self) -> int:
        return self.capacity

    def active_count(self) -> int:
        """Live activations: hashed slots + dense keys actually touched
        (dense pre-provisioning reserves keyspace; activation is first
        touch — the dense_active bitmap)."""
        return len(self.key_to_slot) + int(self.dense_active.sum())

    # -- hot-spot telemetry (consumed by orleans_tpu.rebalance) -----------
    # All four accessors are under the tick fence: record_hits DONATES
    # the counter buffer (_accumulate_hits, donate_argnums=0) and runs
    # inside off-loop worker batches — an unfenced loop-side read could
    # materialize the donated (deleted) array, and an unfenced reset
    # could be overwritten by a worker accumulate over pre-reset
    # counters (double-counted load, defeating the int32-overflow
    # protection the reset exists for).
    def enable_hit_tracking(self) -> None:
        with self.fence:
            if self.hits is None:
                self.hits = self._put(
                    jnp.zeros((self.n_shards, self.capacity + 1),
                              jnp.int32))

    def record_hits(self, slots_b, valid_b, scale: int = 1) -> None:
        """Fold one tick's [n_shards, B] batch into the per-slot counters
        (no-op until enable_hit_tracking). ``scale``: messages per lane —
        K for a scanned K-round kernel. Reentrant under the engine fence
        the tick paths already hold."""
        with self.fence:
            if self.hits is None:
                return
            self.hits = _accumulate_hits(
                self.hits, jnp.asarray(slots_b, jnp.int32),
                jnp.asarray(valid_b), jnp.int32(scale))

    def shard_hits(self) -> np.ndarray:
        """[n_shards] invocation totals since the last reset (sink row
        excluded) — the load view a rebalance planner reads."""
        with self.fence:
            if self.hits is None:
                return np.zeros(self.n_shards, dtype=np.int64)
            return np.asarray(
                jnp.sum(self.hits[:, :self.capacity],
                        axis=1)).astype(np.int64)

    def slot_hits(self) -> np.ndarray:
        """Host copy of the per-slot counters [n_shards, capacity+1]
        (planner-rate readout, not tick-rate)."""
        with self.fence:
            if self.hits is None:
                return np.zeros((self.n_shards, self.capacity + 1),
                                np.int32)
            return np.asarray(self.hits)

    def reset_hits(self) -> None:
        """Zero the counters (each rebalance round plans against the load
        observed since the previous round)."""
        with self.fence:
            if self.hits is not None:
                self.hits = self._put(
                    jnp.zeros((self.n_shards, self.capacity + 1),
                              jnp.int32))

    # -- cost attribution (consumed by observability.ledger) --------------
    # The hit-counter discipline verbatim (same donation, same fence —
    # see the comment block above): the cost buffer is one more masked
    # scatter-add folded into the tick, reusing _accumulate_hits with
    # the per-row µs charge as the scale.
    def enable_cost_tracking(self) -> None:
        with self.fence:
            if self.cost is None:
                self.cost = self._put(
                    jnp.zeros((self.n_shards, self.capacity + 1),
                              jnp.int32))

    def record_cost(self, slots_b, valid_b, cost_us: int) -> None:
        """Fold one tick's [n_shards, B] batch into the per-slot cost
        accumulators: every valid lane is charged ``cost_us``
        microseconds (the tick wall — each resident row occupied the
        whole tick). No-op until enable_cost_tracking; reentrant under
        the engine fence like record_hits."""
        with self.fence:
            if self.cost is None or cost_us <= 0:
                return
            self.cost = _accumulate_hits(
                self.cost, jnp.asarray(slots_b, jnp.int32),
                jnp.asarray(valid_b), jnp.int32(cost_us))

    def slot_cost(self) -> np.ndarray:
        """Host copy of the per-slot cost µs [n_shards, capacity+1]
        (ledger/planner-rate readout, not tick-rate)."""
        with self.fence:
            if self.cost is None:
                return np.zeros((self.n_shards, self.capacity + 1),
                                np.int32)
            return np.asarray(self.cost)

    def cost_seconds(self) -> float:
        """Total charged row-seconds since the last reset, folded ON
        DEVICE via ``ops.segment_reduce.masked_reduce`` (sink column
        masked out) — ONE scalar crosses the host boundary, the DrJAX
        masked-reduction shape the ledger's readout rides."""
        with self.fence:
            if self.cost is None:
                return 0.0
            from ..ops.segment_reduce import masked_reduce
            valid = jnp.broadcast_to(
                jnp.arange(self.capacity + 1) < self.capacity,
                (self.n_shards, self.capacity + 1))
            total = masked_reduce(self.cost, valid, "sum")
            return float(np.asarray(total)) * 1e-6

    def reset_cost(self) -> None:
        """Zero the cost accumulators (int32-overflow protection, same
        rationale as reset_hits)."""
        with self.fence:
            if self.cost is not None:
                self.cost = self._put(
                    jnp.zeros((self.n_shards, self.capacity + 1),
                              jnp.int32))

    # -- dense regime -----------------------------------------------------
    def ensure_dense(self, n: int) -> None:
        """Pre-provision keys 0..n-1 with the static dense mapping. Must be
        called before any hashed allocation (the two regimes share slots
        only if dense claims the low slot range first).

        The mapping is BLOCK-wise — key → (key // per_shard, key % per_shard)
        — so a contiguous key range is an exact reshape onto the
        [n_shards, B] batch layout (zero-shuffle bulk dispatch)."""
        if self.key_to_slot:
            raise RuntimeError("dense mapping must be set up before hashed keys")
        if self.dense_per_shard:
            # the block mapping is frozen at first provisioning: changing
            # per_shard would remap every existing key to another row
            # (silent cross-actor state leak); growth within the provisioned
            # keyspace is free, beyond it requires migration
            if n <= self.dense_per_shard * self.n_shards:
                if n > self.dense_n:
                    self.dense_active = np.concatenate(
                        [self.dense_active, np.zeros(n - self.dense_n, bool)])
                    self.dense_n = n
                return
            raise RuntimeError(
                f"dense keyspace exhausted ({n} > "
                f"{self.dense_per_shard * self.n_shards}); provision the "
                f"maximum population in the first ensure_dense call")
        per_shard = -(-n // self.n_shards)  # ceil
        if per_shard > self.capacity:
            self.grow(per_shard)
        self.dense_n = n
        self.dense_per_shard = per_shard
        # host-side activation bitmap: which dense keys have been fresh-
        # initialized (the OnActivate bookkeeping for the dense regime)
        self.dense_active = np.zeros(n, dtype=bool)
        # carve dense slots out of the free lists
        for s in range(self.n_shards):
            self.free[s] = [i for i in self.free[s]
                            if i >= self.dense_per_shard]

    def dense_fresh_mask(self, keys: np.ndarray) -> np.ndarray | None:
        """Bool [M] mask of dense keys not yet activated, or None when every
        key is already active (the common steady-state — no upload needed)."""
        if self.dense_active.size == 0:
            return None
        m = ~self.dense_active[keys]
        return m if m.any() else None

    def mark_dense_active(self, keys: np.ndarray) -> None:
        if self.dense_active.size:
            self.dense_active[keys] = True

    def dense_shard_slot(self, keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Vectorized key→(shard, slot) for dense keys (int array)."""
        per = max(self.dense_per_shard, 1)
        return keys // per, keys % per

    # -- hashed regime ----------------------------------------------------
    def lookup_or_allocate(self, key_hash: int) -> tuple[int, int, bool]:
        """Returns (shard, slot, fresh)."""
        loc = self.key_to_slot.get(key_hash)
        if loc is not None:
            return loc[0], loc[1], False
        shard = key_hash % self.n_shards
        if not self.free[shard]:
            self.grow(self.capacity * 2)
        slot = self.free[shard].pop()
        self.key_to_slot[key_hash] = (shard, slot)
        self.device_dir.insert(key_hash, self._encode_loc(shard, slot))
        return shard, slot, True

    def _encode_loc(self, shard: int, slot: int) -> int:
        """Pack (shard, slot) into one int32 directory value. Slots are
        encoded against a fixed 2^20 stride (not the live capacity) so
        values survive table growth without re-encoding the directory."""
        assert slot < _LOC_STRIDE
        return shard * _LOC_STRIDE + slot

    def lookup(self, key_hash: int) -> tuple[int, int] | None:
        return self.key_to_slot.get(key_hash)

    def release(self, key_hash: int) -> bool:
        """Free a slot (deactivation). The row data is left in place; the
        slot is reused by the next activation (fresh-init overwrites it)."""
        loc = self.key_to_slot.pop(key_hash, None)
        if loc is None:
            return False
        self.free[loc[0]].append(loc[1])
        self.device_dir.remove(key_hash)
        self.route_hash.pop(key_hash, None)
        return True

    def move_rows(self, keys, dest_shards) -> int:
        """Tick-fenced wrapper (see ``fence``): a shard move gathers and
        scatters ``state``, which must never interleave with an off-loop
        tick whose donated state is mid-dispatch. The key-level fencing
        contract (no pending/in-flight invocation for a moving key) stays
        the caller's job via ``VectorRuntime.pending_key_hashes``."""
        with self.fence:
            return self._move_rows(keys, dest_shards)

    def _move_rows(self, keys, dest_shards) -> int:
        """Live-migrate hashed-regime rows to new shards: extract the state
        rows, insert them at freshly-allocated slots on the destination
        shards, and atomically re-point the host directory maps + the
        on-device DeviceDirectory64 (the executor half of
        orleans_tpu.rebalance; the reference's activation repartitioning
        move, re-expressed as one batched gather+scatter).

        Keys not resident, already on their destination, or whose
        destination shard has no free slot are skipped. The caller is
        responsible for fencing (no pending invocation may hold a stale
        (shard, slot) for a moving key). Returns the number of rows moved;
        on device failure nothing is mutated (the copy is functional and
        the slot/directory bookkeeping only commits after it succeeds)."""
        src_sh, src_sl, dst_sh, dst_sl, moved_keys = [], [], [], [], []
        taken: dict[int, int] = {}  # dest shard → slots claimed this call
        seen: set[int] = set()  # a duplicate key would free its source
        # slot twice and leak a destination slot — skip repeats
        for key, dest in zip(keys, dest_shards):
            key, dest = int(key), int(dest)
            loc = self.key_to_slot.get(key)
            if key in seen or loc is None or loc[0] == dest or \
                    not (0 <= dest < self.n_shards):
                continue
            seen.add(key)
            n_taken = taken.get(dest, 0)
            if n_taken >= len(self.free[dest]):
                continue  # destination full: skip, never grow mid-move
            taken[dest] = n_taken + 1
            src_sh.append(loc[0])
            src_sl.append(loc[1])
            dst_sh.append(dest)
            # peek (no pop) so failure below leaves the free lists intact
            dst_sl.append(self.free[dest][-1 - n_taken])
            moved_keys.append(key)
        if not moved_keys:
            return 0
        idx = (jnp.asarray(src_sh, jnp.int32), jnp.asarray(src_sl, jnp.int32),
               jnp.asarray(dst_sh, jnp.int32), jnp.asarray(dst_sl, jnp.int32))
        new_state = _move_state_rows(self.state, *idx)
        if self.hits is not None:
            # counters travel with the row (the planner's next view must
            # see the key's heat at its new home, not a ghost at the old)
            moved_hits = self.hits[idx[0], idx[1]]
            self.hits = self.hits.at[idx[2], idx[3]].set(moved_hits) \
                .at[idx[0], idx[1]].set(0)
        if self.cost is not None:
            # charged cost travels with the row too (same ghost rule)
            moved_cost = self.cost[idx[0], idx[1]]
            self.cost = self.cost.at[idx[2], idx[3]].set(moved_cost) \
                .at[idx[0], idx[1]].set(0)
        self.state = new_state  # commit point
        for key, s_sh, s_sl, d_sh, d_sl in zip(
                moved_keys, src_sh, src_sl, dst_sh, dst_sl):
            self.free[d_sh].remove(d_sl)
            self.free[s_sh].append(s_sl)
            self.key_to_slot[key] = (d_sh, d_sl)
            self.device_dir.remove(key)
            self.device_dir.insert(key, self._encode_loc(d_sh, d_sl))
        return len(moved_keys)

    def note_route(self, key_hash: int, uniform_hash: int) -> None:
        """Record the routing hash for a (resident or incoming) hashed
        key — every entry point that knows the GrainId calls this."""
        if key_hash != uniform_hash:
            self.route_hash[key_hash] = uniform_hash

    def note_route_many(self, pairs) -> None:
        """Batched :meth:`note_route` — worker-process proxies buffer
        their (key_hash, uniform_hash) notes and ship them with the
        packed call record, so the ownership sweep sees the same routes
        it would have in-process (the pairs arrive pre-filtered:
        proxies only buffer key_hash != uniform_hash)."""
        self.route_hash.update(pairs)

    def unowned_keys(self, still_owned) -> list[int]:
        """Hashed-regime rows whose ring ownership left this silo (the
        membership-change sweep's release set). A row surviving on an
        ex-owner is a STALE COPY — if ownership ever returns, serving it
        would fork the key's state from what the interim owner wrote
        (and persisted); releasing forces recovery-on-first-touch from
        storage instead. The host-tier analog is activation deactivation
        on directory re-registration. Dense-regime rows are NOT swept
        (their multi-silo re-range is the explicit reshard_dense path).
        Keys with no recorded route hash use the key hash itself — exact
        for non-int keys (whose key_hash IS the uniform hash) and for
        every key that entered through a routed call; bulk-loaded int
        keys must have had note_route called (the bridge does)."""
        return [kh for kh in self.key_to_slot
                if not still_owned(self.route_hash.get(kh, kh))]

    # -- growth -----------------------------------------------------------
    def grow(self, new_capacity: int) -> None:
        """Grow every shard's slot pool (doubling amortizes recompiles —
        kernels specialize on capacity). Under the tick fence when the
        owning engine runs off-loop: growth swaps ``state`` wholesale and
        re-points the staging sink, so it must never interleave with a
        worker-side batch that read the old state (the worker would
        commit a pre-growth tree over the grown one and truncate every
        row above the old capacity)."""
        with self.fence:
            return self._grow(new_capacity)

    def _grow(self, new_capacity: int) -> None:
        new_capacity = max(new_capacity, self.capacity * 2)
        # round to power of two to bound the number of distinct kernel shapes
        new_capacity = 1 << (new_capacity - 1).bit_length()
        old = self.capacity
        for name, arr in self.state.items():
            dtype, shape = self.grain_class.STATE[name]
            grown = jnp.zeros(
                (self.n_shards, new_capacity + 1, *shape), dtype=dtype)
            # old sink row (index `old`) is junk; copy only real rows
            grown = grown.at[:, :old].set(arr[:, :old])
            self.state[name] = self._put(grown)
        if self.hits is not None:
            grown_hits = jnp.zeros((self.n_shards, new_capacity + 1),
                                   jnp.int32)
            self.hits = self._put(
                grown_hits.at[:, :old].set(self.hits[:, :old]))
        if self.cost is not None:
            grown_cost = jnp.zeros((self.n_shards, new_capacity + 1),
                                   jnp.int32)
            self.cost = self._put(
                grown_cost.at[:, :old].set(self.cost[:, :old]))
        for s in range(self.n_shards):
            self.free[s] = list(range(new_capacity - 1, old - 1, -1)) + self.free[s]
        self.capacity = new_capacity

    # -- host access (tests, persistence flush) ---------------------------
    def read_row(self, key_hash: int) -> dict[str, np.ndarray] | None:
        with self.fence:  # never materialize a donated-in-flight array
            return self._read_row(key_hash)

    def _read_row(self, key_hash: int) -> dict[str, np.ndarray] | None:
        loc = self.key_to_slot.get(key_hash)
        if loc is None:
            if 0 <= key_hash < self.dense_n:
                loc = (key_hash // self.dense_per_shard,
                       key_hash % self.dense_per_shard)
            else:
                return None
        shard, slot = loc
        return {k: np.asarray(v[shard, slot]) for k, v in self.state.items()}

    def snapshot(self) -> dict[str, np.ndarray]:
        """Full host copy of the state arrays (checkpoint path; orbax-style
        async checkpointing can hook here). Fenced against off-loop ticks
        — a donated in-flight state array cannot be materialized."""
        with self.fence:
            return {k: np.asarray(v) for k, v in self.state.items()}

    def restore(self, snap: dict[str, np.ndarray]) -> None:
        with self.fence:  # a worker batch mid-flight would commit over it
            for k, arr in snap.items():
                self.state[k] = self._put(jnp.asarray(arr))
