"""ShardedActorTable: device-resident activation state, sharded over the mesh.

The fusion of the reference's ``ActivationDirectory`` (local activation map,
ActivationDirectory.cs) and ``GrainDirectoryPartition`` (consistent-hash
ownership, GrainDirectoryPartition.cs:207) re-expressed as device arrays
(SURVEY.md §7): activation state for one VectorGrain class lives in a slot
pool of shape ``[n_shards, capacity+1, *field]`` sharded over the ``silo``
mesh axis. Slot ``capacity`` (the last row) is a write sink for padding
lanes, so masked scatters never collide with real rows.

Key → shard is ``uniform_hash % n_shards`` (the ring's CalculateTargetSilo,
LocalGrainDirectory.cs:477, degenerated to a static mesh mapping); slot
within the shard comes from a host-side free list (the dynamic-activation-
table hard part: slot pool + free list, SURVEY.md §7 hard parts #2).

Two key regimes:
* **hashed** (general): host dict key→(shard, slot); per-key alloc/free.
* **dense** (bulk workloads, e.g. 1M Presence players with keys 0..N-1):
  ``ensure_dense(n)`` pre-provisions key i → (i % n_shards, i // n_shards)
  so bulk batches compute slots with vectorized integer math — no per-key
  Python. This is the 1M-msgs/sec path.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import SILO_AXIS, make_mesh, shard_spec
from .vector_grain import VectorGrain, vector_methods

# directory-value encoding stride: loc = shard * _LOC_STRIDE + slot.
# Fixed (not the live capacity) so encoded values survive table growth;
# bounds per-shard capacity at 2^20 slots and shards at 2^10 within int32.
_LOC_STRIDE = 1 << 20

__all__ = ["ShardedActorTable"]


class ShardedActorTable:
    def __init__(self, grain_class: type[VectorGrain], mesh=None,
                 capacity_per_shard: int = 1024):
        self.grain_class = grain_class
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_shards = self.mesh.devices.size
        # power-of-two capacity: bounds distinct kernel shapes (grow() keeps
        # this invariant) and lets padded batch buckets (_bucket, also po2)
        # slice the slot pool contiguously in the dense fast path
        self.capacity = 1 << (int(capacity_per_shard) - 1).bit_length()
        self.methods = vector_methods(grain_class)
        # On a 1-device mesh, committed NamedSharding buffers pay a large
        # dispatch/layout penalty through the axon tunnel for zero benefit;
        # plain uncommitted arrays behave identically there.
        self.sharding = shard_spec(self.mesh) if self.n_shards > 1 else None

        # host bookkeeping
        self.key_to_slot: dict[int, tuple[int, int]] = {}  # key_hash → (shard, slot)
        # device-queryable mirror of the hashed-key directory: full 62-bit
        # key identity, value = shard * (capacity+1) ... encoded lazily per
        # lookup as shard/slot below. Lets sparse keys ride the on-device
        # routing path (route/apply_received sparse mode) — the on-chip
        # directory tier (ops.hash_probe; AdaptiveGrainDirectoryCache.cs:178)
        from ..ops.hash_probe import DeviceDirectory64
        self.device_dir = DeviceDirectory64()
        # key_hash → the GrainId uniform hash that ROUTES it (differs for
        # small-int keys, where key_hash is the key itself): ring-ownership
        # sweeps need the routing hash to decide who owns a resident row
        self.route_hash: dict[int, int] = {}
        self.free: list[list[int]] = [
            list(range(self.capacity - 1, -1, -1)) for _ in range(self.n_shards)]
        self.dense_n = 0  # keys [0, dense_n) are dense-mapped
        self.dense_per_shard = 0
        self.dense_active = np.zeros(0, dtype=bool)

        # device state: [n_shards, capacity+1, *shape]; row `capacity` is the
        # padding write sink
        self.state: dict[str, jax.Array] = {}
        for name, (dtype, shape) in grain_class.STATE.items():
            self.state[name] = self._put(
                jnp.zeros((self.n_shards, self.capacity + 1, *shape),
                          dtype=dtype))

    # ------------------------------------------------------------------
    def _put(self, arr):
        """Commit to the mesh sharding (no-op on a 1-device mesh)."""
        return jax.device_put(arr, self.sharding) if self.sharding else arr

    def _put_rounds(self, arr):
        """Commit a [K, n_shards, ...] stacked-rounds array: sharded on the
        shard axis (dim 1), replicated over rounds."""
        if not self.sharding:
            return arr
        from jax.sharding import NamedSharding, PartitionSpec
        return jax.device_put(
            arr, NamedSharding(self.mesh, PartitionSpec(None, SILO_AXIS)))

    @property
    def sink_slot(self) -> int:
        return self.capacity

    def active_count(self) -> int:
        """Live activations: hashed slots + dense keys actually touched
        (dense pre-provisioning reserves keyspace; activation is first
        touch — the dense_active bitmap)."""
        return len(self.key_to_slot) + int(self.dense_active.sum())

    # -- dense regime -----------------------------------------------------
    def ensure_dense(self, n: int) -> None:
        """Pre-provision keys 0..n-1 with the static dense mapping. Must be
        called before any hashed allocation (the two regimes share slots
        only if dense claims the low slot range first).

        The mapping is BLOCK-wise — key → (key // per_shard, key % per_shard)
        — so a contiguous key range is an exact reshape onto the
        [n_shards, B] batch layout (zero-shuffle bulk dispatch)."""
        if self.key_to_slot:
            raise RuntimeError("dense mapping must be set up before hashed keys")
        if self.dense_per_shard:
            # the block mapping is frozen at first provisioning: changing
            # per_shard would remap every existing key to another row
            # (silent cross-actor state leak); growth within the provisioned
            # keyspace is free, beyond it requires migration
            if n <= self.dense_per_shard * self.n_shards:
                if n > self.dense_n:
                    self.dense_active = np.concatenate(
                        [self.dense_active, np.zeros(n - self.dense_n, bool)])
                    self.dense_n = n
                return
            raise RuntimeError(
                f"dense keyspace exhausted ({n} > "
                f"{self.dense_per_shard * self.n_shards}); provision the "
                f"maximum population in the first ensure_dense call")
        per_shard = -(-n // self.n_shards)  # ceil
        if per_shard > self.capacity:
            self.grow(per_shard)
        self.dense_n = n
        self.dense_per_shard = per_shard
        # host-side activation bitmap: which dense keys have been fresh-
        # initialized (the OnActivate bookkeeping for the dense regime)
        self.dense_active = np.zeros(n, dtype=bool)
        # carve dense slots out of the free lists
        for s in range(self.n_shards):
            self.free[s] = [i for i in self.free[s]
                            if i >= self.dense_per_shard]

    def dense_fresh_mask(self, keys: np.ndarray) -> np.ndarray | None:
        """Bool [M] mask of dense keys not yet activated, or None when every
        key is already active (the common steady-state — no upload needed)."""
        if self.dense_active.size == 0:
            return None
        m = ~self.dense_active[keys]
        return m if m.any() else None

    def mark_dense_active(self, keys: np.ndarray) -> None:
        if self.dense_active.size:
            self.dense_active[keys] = True

    def dense_shard_slot(self, keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Vectorized key→(shard, slot) for dense keys (int array)."""
        per = max(self.dense_per_shard, 1)
        return keys // per, keys % per

    # -- hashed regime ----------------------------------------------------
    def lookup_or_allocate(self, key_hash: int) -> tuple[int, int, bool]:
        """Returns (shard, slot, fresh)."""
        loc = self.key_to_slot.get(key_hash)
        if loc is not None:
            return loc[0], loc[1], False
        shard = key_hash % self.n_shards
        if not self.free[shard]:
            self.grow(self.capacity * 2)
        slot = self.free[shard].pop()
        self.key_to_slot[key_hash] = (shard, slot)
        self.device_dir.insert(key_hash, self._encode_loc(shard, slot))
        return shard, slot, True

    def _encode_loc(self, shard: int, slot: int) -> int:
        """Pack (shard, slot) into one int32 directory value. Slots are
        encoded against a fixed 2^20 stride (not the live capacity) so
        values survive table growth without re-encoding the directory."""
        assert slot < _LOC_STRIDE
        return shard * _LOC_STRIDE + slot

    def lookup(self, key_hash: int) -> tuple[int, int] | None:
        return self.key_to_slot.get(key_hash)

    def release(self, key_hash: int) -> bool:
        """Free a slot (deactivation). The row data is left in place; the
        slot is reused by the next activation (fresh-init overwrites it)."""
        loc = self.key_to_slot.pop(key_hash, None)
        if loc is None:
            return False
        self.free[loc[0]].append(loc[1])
        self.device_dir.remove(key_hash)
        self.route_hash.pop(key_hash, None)
        return True

    def note_route(self, key_hash: int, uniform_hash: int) -> None:
        """Record the routing hash for a (resident or incoming) hashed
        key — every entry point that knows the GrainId calls this."""
        if key_hash != uniform_hash:
            self.route_hash[key_hash] = uniform_hash

    def unowned_keys(self, still_owned) -> list[int]:
        """Hashed-regime rows whose ring ownership left this silo (the
        membership-change sweep's release set). A row surviving on an
        ex-owner is a STALE COPY — if ownership ever returns, serving it
        would fork the key's state from what the interim owner wrote
        (and persisted); releasing forces recovery-on-first-touch from
        storage instead. The host-tier analog is activation deactivation
        on directory re-registration. Dense-regime rows are NOT swept
        (their multi-silo re-range is the explicit reshard_dense path).
        Keys with no recorded route hash use the key hash itself — exact
        for non-int keys (whose key_hash IS the uniform hash) and for
        every key that entered through a routed call; bulk-loaded int
        keys must have had note_route called (the bridge does)."""
        return [kh for kh in self.key_to_slot
                if not still_owned(self.route_hash.get(kh, kh))]

    # -- growth -----------------------------------------------------------
    def grow(self, new_capacity: int) -> None:
        """Grow every shard's slot pool (doubling amortizes recompiles —
        kernels specialize on capacity)."""
        new_capacity = max(new_capacity, self.capacity * 2)
        # round to power of two to bound the number of distinct kernel shapes
        new_capacity = 1 << (new_capacity - 1).bit_length()
        old = self.capacity
        for name, arr in self.state.items():
            dtype, shape = self.grain_class.STATE[name]
            grown = jnp.zeros(
                (self.n_shards, new_capacity + 1, *shape), dtype=dtype)
            # old sink row (index `old`) is junk; copy only real rows
            grown = grown.at[:, :old].set(arr[:, :old])
            self.state[name] = self._put(grown)
        for s in range(self.n_shards):
            self.free[s] = list(range(new_capacity - 1, old - 1, -1)) + self.free[s]
        self.capacity = new_capacity

    # -- host access (tests, persistence flush) ---------------------------
    def read_row(self, key_hash: int) -> dict[str, np.ndarray] | None:
        loc = self.key_to_slot.get(key_hash)
        if loc is None:
            if 0 <= key_hash < self.dense_n:
                loc = (key_hash // self.dense_per_shard,
                       key_hash % self.dense_per_shard)
            else:
                return None
        shard, slot = loc
        return {k: np.asarray(v[shard, slot]) for k, v in self.state.items()}

    def snapshot(self) -> dict[str, np.ndarray]:
        """Full host copy of the state arrays (checkpoint path; orbax-style
        async checkpointing can hook here)."""
        return {k: np.asarray(v) for k, v in self.state.items()}

    def restore(self, snap: dict[str, np.ndarray]) -> None:
        for k, arr in snap.items():
            self.state[k] = self._put(jnp.asarray(arr))
