"""Device-tier stateless workers: a VectorGrain class REPLICATED over the
mesh axis — the device analog of ``[StatelessWorker]``
(/root/reference/src/Orleans.Core.Abstractions/Placement/
StatelessWorkerPlacement.cs:6, StatelessWorkerDirector.cs:8; SURVEY §2.4
"replicate actor class across mesh axis; no directory entry").

Semantics, mapped tpu-first:

* **No directory entry / no owner**: every shard holds its own replica row
  for every key; a call for key k may run on ANY shard (assignment is
  round-robin — the stateless-worker scale-out: work spreads over the
  mesh instead of hashing to one owner).
* **Workers are independent**: per-shard replicas diverge by design, like
  N stateless-worker activations of the same grain each accumulating
  local state (the reference's canonical use: local caches/aggregators).
* **Reads fan in via collectives**: :meth:`ReplicatedWorkerHost.read_merged`
  folds the per-shard replicas with the class's ``MERGE`` spec — one
  ``psum`` / ``pmax`` / ``pmin`` over the silo axis per field — so a read
  sees the cluster-wide aggregate without any cross-shard messaging.

Classes opt in with :func:`replicated_worker` and declare how fields merge::

    @replicated_worker
    class HitCounter(VectorGrain):
        STATE = {"hits": (jnp.int32, ()), "peak": (jnp.int32, ())}
        MERGE = {"hits": "sum", "peak": "max"}
        ...

Hosted through ``VectorRuntime.replicated_host(cls, n_keys)``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import (SILO_AXIS, replicated_spec, shard_map_compat,
                             shard_spec)
from .engine import _validate_args
from .vector_grain import VectorGrain, vector_methods

__all__ = ["replicated_worker", "ReplicatedWorkerHost"]

_MERGE_COLLECTIVES = {
    "sum": jax.lax.psum,
    "max": jax.lax.pmax,
    "min": jax.lax.pmin,
}


def replicated_worker(cls: type) -> type:
    """Mark a VectorGrain class for mesh-axis replication. Requires a
    ``MERGE`` dict naming a collective ("sum" | "max" | "min") per STATE
    field — the read fan-in semantics."""
    merge = getattr(cls, "MERGE", None)
    if not isinstance(merge, dict) or set(merge) != set(cls.STATE):
        raise TypeError(
            f"{cls.__name__} needs MERGE covering exactly its STATE fields "
            f"({sorted(cls.STATE)}); got {merge!r}")
    bad = {f: op for f, op in merge.items() if op not in _MERGE_COLLECTIVES}
    if bad:
        raise TypeError(f"unknown merge ops {bad}; choose from "
                        f"{sorted(_MERGE_COLLECTIVES)}")
    cls.__vector_replicated__ = True
    return cls


class ReplicatedWorkerHost:
    """Replicated table + dispatch for one stateless-worker class.

    State layout: ``[n_shards, n_keys + 1, *field]`` (row ``n_keys`` is
    the padding write sink), committed to the mesh sharding on the shard
    axis — each device owns ITS replica block, exactly like the sharded
    actor table, but the key space is the full range on every shard."""

    def __init__(self, cls: type[VectorGrain], mesh, n_keys: int):
        if not getattr(cls, "__vector_replicated__", False):
            raise TypeError(
                f"{cls.__name__} is not @replicated_worker-decorated")
        self.cls = cls
        self.mesh = mesh
        self.n_shards = mesh.devices.size
        self.n_keys = int(n_keys)
        self.methods = vector_methods(cls)
        self._sharding = shard_spec(mesh) if self.n_shards > 1 else None
        self._replicated = replicated_spec(mesh) if self.n_shards > 1 \
            else None
        self._rr = 0  # round-robin shard assignment (the scale-out knob)
        # per-(shard, key) activation bitmap: first touch runs
        # initial_state on that shard's replica row (OnActivate per
        # stateless-worker activation)
        self.active = np.zeros((self.n_shards, self.n_keys), dtype=bool)
        self.state: dict[str, jax.Array] = {}
        for name, (dtype, shape) in cls.STATE.items():
            self.state[name] = self._put(jnp.zeros(
                (self.n_shards, self.n_keys + 1, *shape), dtype=dtype))
        self._kernel_cache: dict[tuple, Any] = {}
        self.calls = 0

    def _put(self, arr):
        return jax.device_put(arr, self._sharding) if self._sharding \
            else arr

    # ------------------------------------------------------------------
    def call_batch(self, method: str, keys: np.ndarray,
                   args: dict[str, np.ndarray] | None = None):
        """Run ``method`` for each key on a round-robin-assigned shard,
        in as many kernel ticks as duplicate pressure requires; returns
        results in caller order.

        Duplicate keys spread over shards (independent workers run in
        parallel); when more than one call lands on the same (shard, key)
        they serialize across ticks — one turn per worker per tick, like
        the owned table's conflict defer. No call is ever dropped."""
        m = self.methods.get(method)
        if m is None:
            raise AttributeError(
                f"{self.cls.__name__} has no @actor_method {method!r}")
        keys = np.asarray(keys)
        self._check_keys(keys)
        M = keys.shape[0]
        args = args or {}
        n = self.n_shards
        if m.args_schema is None and args:
            m.args_schema = {k: (np.asarray(v).dtype,
                                 np.asarray(v).shape[1:])
                             for k, v in args.items()}
        if m.args_schema is not None:
            _validate_args(self.cls, method, m.args_schema, args)
        shard = (np.arange(self._rr, self._rr + M) % n).astype(np.int64)
        self._rr = int((self._rr + M) % n)
        results_by_idx: list = [None] * M
        remaining = list(range(M))
        while remaining:
            claimed: set = set()
            this_round: list = []
            deferred: list = []
            for idx in remaining:
                loc = (shard[idx], int(keys[idx]))
                if loc in claimed:
                    deferred.append(idx)
                else:
                    claimed.add(loc)
                    this_round.append(idx)
            self._one_tick(m, method, keys, args, shard, this_round,
                           results_by_idx)
            remaining = deferred
        self.calls += M
        if not results_by_idx:
            return np.zeros(0)
        return jax.tree_util.tree_map(
            lambda *xs: np.stack(xs), *results_by_idx)

    def _one_tick(self, m, method: str, keys, args, shard,
                  idxs: list, results_by_idx: list) -> None:
        n = self.n_shards
        sh = shard[idxs]
        ks = keys[idxs]
        counts = np.bincount(sh, minlength=n)
        B = max(8, 1 << int(counts.max() - 1).bit_length())
        order = np.argsort(sh, kind="stable")
        starts = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(counts, out=starts[1:])
        lane = np.arange(len(idxs)) - starts[sh[order]]
        slots = np.full((n, B), self.n_keys, dtype=np.int32)
        valid = np.zeros((n, B), dtype=bool)
        fresh = np.zeros((n, B), dtype=bool)
        slots[sh[order], lane] = ks[order]
        valid[sh[order], lane] = True
        fresh[sh[order], lane] = ~self.active[sh[order], ks[order]]
        if not m.read_only:
            # a read-only first touch views initial_state in-kernel but
            # persists nothing — the key stays fresh so the first WRITE
            # still runs initial_state (otherwise a nonzero initial state
            # would be silently replaced by the zero fill)
            self.active[sh[order], ks[order]] = True
        args_b = {}
        for fname, (dtype, shape) in (m.args_schema or {}).items():
            buf = np.zeros((n, B, *shape), dtype=dtype)
            buf[sh[order], lane] = \
                np.asarray(args[fname], dtype=dtype)[idxs][order]
            args_b[fname] = self._put(jnp.asarray(buf))
        kern = self._tick_kernel(method, B)
        new_state, results = kern(
            self.state, self._put(jnp.asarray(slots)),
            self._put(jnp.asarray(fresh)), self._put(jnp.asarray(valid)),
            args_b)
        if not m.read_only:
            self.state = new_state
        host = jax.tree_util.tree_map(np.asarray, results)
        for pos, idx in enumerate(np.asarray(idxs)[order]):
            results_by_idx[idx] = jax.tree_util.tree_map(
                lambda a, p=pos: a[sh[order][p], lane[p]], host)

    def _check_keys(self, keys: np.ndarray) -> None:
        if keys.size and (keys.min() < 0 or keys.max() >= self.n_keys):
            raise ValueError(
                f"{self.cls.__name__} keys must be in [0, {self.n_keys}); "
                f"got range [{keys.min()}, {keys.max()}]")

    def _tick_kernel(self, method: str, B: int):
        key = ("tick", method, B, self.n_keys)
        k = self._kernel_cache.get(key)
        if k is not None:
            return k
        m = self.methods[method]
        handler, init = m.fn, self.cls.initial_state
        read_only = m.read_only

        def sel(mask, a, b):
            return jnp.where(
                mask.reshape(mask.shape + (1,) * (a.ndim - 1)), a, b)

        def local(state, slots, fresh, valid, args):
            st = jax.tree_util.tree_map(lambda a: a[0], state)
            slots_l, fresh_l, valid_l = slots[0], fresh[0], valid[0]
            args_l = jax.tree_util.tree_map(lambda a: a[0], args)
            rows = jax.tree_util.tree_map(lambda f: f[slots_l], st)
            init_rows = jax.vmap(init)(slots_l.astype(jnp.int32))
            rows = jax.tree_util.tree_map(
                lambda ir, r: sel(fresh_l, ir, r), init_rows, rows)
            new_rows, results = jax.vmap(handler)(rows, args_l)
            if read_only:
                out = state
            else:
                new_st = jax.tree_util.tree_map(
                    lambda f, nr, r: f.at[slots_l].set(
                        sel(valid_l, nr, r)), st, new_rows, rows)
                out = jax.tree_util.tree_map(lambda a: a[None], new_st)
            return out, jax.tree_util.tree_map(lambda a: a[None], results)

        if self.n_shards > 1:
            spec = P(SILO_AXIS)
            local = shard_map_compat(
                local, mesh=self.mesh,
                in_specs=(spec, spec, spec, spec, spec),
                out_specs=(spec, spec), check_vma=False)
        # donation only when state is actually replaced: a read-only tick
        # keeps self.state pointing at the input arrays, which donation
        # would have invalidated (engine._build_kernel guards identically)
        k = jax.jit(local, donate_argnums=(0,) if not read_only else ())
        self._kernel_cache[key] = k
        return k

    # ------------------------------------------------------------------
    def read_merged(self, keys: np.ndarray) -> dict[str, np.ndarray]:
        """Cluster-wide view of ``keys``: every shard reads its replica
        rows, then ONE collective per field folds them with the class's
        MERGE spec (psum/pmax/pmin over the silo axis) — the read fan-in
        of N stateless workers, with zero cross-shard messages."""
        keys = np.asarray(keys, dtype=np.int32)
        self._check_keys(keys)
        kern = self._merge_kernel(keys.shape[0])
        d_keys = jax.device_put(jnp.asarray(keys), self._replicated) \
            if self._replicated else jnp.asarray(keys)
        out = kern(self.state, d_keys)
        return jax.tree_util.tree_map(np.asarray, out)

    def _merge_kernel(self, M: int):
        key = ("merge", M)
        k = self._kernel_cache.get(key)
        if k is not None:
            return k
        merge = self.cls.MERGE
        # never merge uninitialized replica rows as real zeros for
        # max/min of signed data? zeros are the declared initial fill of
        # the table; initial_state defines per-actor semantics on first
        # touch per shard. Untouched shards contribute the zero fill —
        # the documented contract (stateless workers that never saw a
        # key contribute the identity only if initial_state is the zero
        # fill; classes needing a different identity must encode it in
        # their merge field choice).

        # static closure value, hoisted deliberately (like `merge` above):
        # reading self.* inside the traced body would freeze host object
        # state into the kernel invisibly (OTPU006) — the shard count is a
        # trace-time constant by construction (mesh size is fixed for the
        # host's lifetime and the kernel cache is per-shape)
        sharded = self.n_shards > 1

        def local(state, keys):
            st = jax.tree_util.tree_map(lambda a: a[0], state)
            rows = {f: st[f][keys] for f in st}
            if sharded:
                rows = {f: _MERGE_COLLECTIVES[merge[f]](v, SILO_AXIS)
                        for f, v in rows.items()}
            return jax.tree_util.tree_map(lambda a: a[None], rows)

        if sharded:
            local = shard_map_compat(
                local, mesh=self.mesh, in_specs=(P(SILO_AXIS), P()),
                out_specs=P(None), check_vma=False)

        def run(state, keys):
            out = local(state, keys)
            return jax.tree_util.tree_map(lambda a: a[0], out)

        k = jax.jit(run)
        self._kernel_cache[key] = k
        return k
