"""Device-tier batched dispatch: VectorGrain, sharded actor tables, tick
engine (the TPU-native replacement for the reference's per-message hot path,
SURVEY.md §7)."""

from .engine import VectorActorRef, VectorRuntime  # noqa: F401
from .hosting import add_vector_grains  # noqa: F401
from .replicated import ReplicatedWorkerHost, replicated_worker  # noqa: F401
from .reshard import reshard_dense  # noqa: F401
from .table import ShardedActorTable  # noqa: F401
from .vector_grain import VectorGrain, actor_method  # noqa: F401
