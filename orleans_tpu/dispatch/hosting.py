"""Host the device tier inside a silo (the two-tier catalog of SURVEY §7
hard parts #1, and the north-star interception: the silo's message loop
hands vector-interface requests to the batched kernel engine instead of
per-activation turns).

``add_vector_grains(builder, PlayerGrain, ...)`` installs a VectorRuntime
on the silo and registers each class's interface; after that, ordinary
clients call device-tier actors exactly like host grains —

    client.get_grain(PlayerGrain, 42).heartbeat(pos=...)

— and concurrent calls from any number of clients coalesce into per-tick
kernels. Gateway affinity (target-grain-hash routing in the client message
centers) keeps one key's calls on one silo, so per-silo tables act as the
cluster's key partition without a directory entry per actor.
"""

from __future__ import annotations

from .engine import VectorRuntime
from .vector_grain import VectorGrain

__all__ = ["add_vector_grains"]


def add_vector_grains(builder, *grain_classes: type[VectorGrain],
                      mesh=None, capacity_per_shard: int = 1024,
                      dense: dict[type, int] | None = None,
                      options=None, storage=None,
                      flush_period: float = 1.0,
                      checkpoint_dir: str | None = None,
                      checkpoint_period: float = 30.0,
                      checkpoint_keep: int = 3):
    """Register device-tier grain classes on a SiloBuilder.

    ``dense``: optional {class: n} pre-provisioning keys 0..n-1 with the
    zero-shuffle dense mapping (the bulk regime). ``options``: a
    config.DispatchOptions group (overrides capacity_per_shard).

    ``storage``: a GrainStorage provider enabling write-behind persistence
    (the TpuGrainStorage of the north-star design): keys written by ticks
    are tracked and their device rows flushed every ``flush_period``
    seconds via storage.checkpoint.VectorStorageBridge, with a final flush
    at silo stop. Resume stays per-actor-lazy: ``silo.vector_bridges[cls]
    .load(keys)`` rehydrates rows (the virtual-actor rebuild contract).

    ``checkpoint_dir``: enables periodic whole-table orbax snapshots
    (storage.checkpoint.VectorCheckpointer) every ``checkpoint_period``
    seconds, keeping ``checkpoint_keep`` — the whole-silo resume path. If
    a checkpoint exists at start, the silo restores it before serving.
    """
    for cls in grain_classes:
        if not issubclass(cls, VectorGrain):
            raise TypeError(f"{cls.__name__} is not a VectorGrain")

    def install(silo) -> None:
        import asyncio

        if silo.vector is None:
            silo.vector = VectorRuntime(
                mesh=mesh, capacity_per_shard=capacity_per_shard,
                options=options)
        # off-loop tick pipeline: silo-hosted runtimes take the lever
        # from SiloConfig (the A/B switch; DispatchOptions.offloop_tick
        # only governs standalone engines)
        silo.vector.offloop_tick = silo.config.offloop_tick
        if silo.tracer is not None:
            silo.vector.tracer = silo.tracer  # device ticks join the traces
        if silo.ingest_stats is not None:
            # device-half ingest attribution (staging/transfer/tick land
            # in the silo's registry beside the host-side stages)
            silo.vector.stats = silo.ingest_stats
        if silo.shed_trend is not None:
            # device-tier queue-wait feeds the same load-shed trend the
            # host turns feed (vector-heavy overload sheds too)
            silo.vector.shed_trend = silo.shed_trend
        if silo.ledger is not None:
            # cost attribution: batch epilogues charge the silo's ledger
            # and the tables grow the on-device per-slot cost twin
            silo.vector.ledger = silo.ledger
            silo.vector.enable_cost_tracking()
        silo.vector.register(*grain_classes)
        for cls in grain_classes:
            silo.vector_interfaces[cls.__name__] = cls
        for cls, n in (dense or {}).items():
            silo.vector.table(cls).ensure_dense(n)
        _install_ownership_sweep(silo)
        if checkpoint_dir is not None:
            _install_checkpoints(silo)
        if storage is None:
            return

        from ..storage.checkpoint import VectorStorageBridge

        silo.vector.enable_dirty_tracking()
        if not hasattr(silo, "vector_bridges"):
            silo.vector_bridges = {}
        for cls in grain_classes:
            silo.vector_bridges[cls] = VectorStorageBridge(
                silo.vector, cls, storage)
        _install_flusher(silo)

    def _install_ownership_sweep(silo) -> None:
        """Membership-change sweep: a silo that loses a key's ring
        ownership must release its resident row — keeping it would serve
        a STALE copy if ownership ever returns (the interim owner wrote
        and persisted newer state), forking the key. Releasing forces
        recovery-on-first-touch, the same rebuild path a fresh owner
        takes. Host-tier analog: duplicate-activation deactivation on
        directory re-registration. Rows with acked-but-unflushed writes
        are flushed FIRST (leave-side handoff: make the tail durable
        before handing the key over) when a write-behind bridge exists."""
        import asyncio
        import logging

        # strong refs: the loop holds tasks weakly, and a GC'd sweep
        # would silently skip the release this mechanism exists for
        sweep_tasks: set = set()

        def on_view_change(alive, dead) -> None:
            async def sweep() -> None:
                await asyncio.sleep(0)  # after the locator applies the view
                me = silo.silo_address
                ring = silo.locator.ring

                def owned(uh: int) -> bool:
                    o = ring.owner(uh)
                    return o is None or o == me

                n = 0
                for cls in grain_classes:
                    tbl = silo.vector.tables.get(cls)
                    if tbl is None or not tbl.key_to_slot:
                        continue
                    gone = tbl.unowned_keys(owned)
                    if not gone:
                        continue
                    bridge = getattr(silo, "vector_bridges", {}).get(cls)
                    if bridge is not None:
                        try:
                            await bridge.flush(gone)
                        except Exception:  # noqa: BLE001 — handoff flush
                            # is best-effort; a conflict means the new
                            # owner already persisted newer state
                            logging.getLogger("orleans.vector").info(
                                "handoff flush failed for %s",
                                cls.__name__, exc_info=True)
                    for kh in gone:
                        tbl.release(kh)
                    n += len(gone)
                if n:
                    silo.stats.increment("vector.ownership.released", n)
                    logging.getLogger("orleans.vector").info(
                        "released %d device-tier rows after ownership "
                        "re-range", n)

            t = asyncio.get_running_loop().create_task(sweep())
            sweep_tasks.add(t)
            t.add_done_callback(sweep_tasks.discard)

        def start() -> None:
            if silo.membership is not None:
                silo.membership.subscribe(on_view_change)

        from ..runtime.silo import ServiceLifecycleStage

        silo.subscribe_lifecycle(
            ServiceLifecycleStage.RUNTIME_GRAIN_SERVICES, start, None)

    def _install_flusher(silo) -> None:
        import asyncio

        state = {"task": None}

        async def flush_all(strict: bool = False) -> int:
            n = 0
            first_error: BaseException | None = None
            for cls in grain_classes:
                keys = silo.vector.drain_dirty(cls)
                if not len(keys):
                    continue
                try:
                    n += await silo.vector_bridges[cls].flush(
                        keys, strict=strict)
                except asyncio.CancelledError:
                    # cancelled mid-flush: the keys are already drained —
                    # re-mark them so the final stop() drain retries
                    # instead of losing them
                    silo.vector._mark_dirty(cls, keys)
                    raise
                except BaseException as e:  # noqa: BLE001
                    # batch-phase failure (e.g. the device→host gather) or
                    # a strict re-raise: re-mark so nothing drained is
                    # lost (per-key write failures were already re-marked
                    # inside flush; re-marking them twice is harmless),
                    # then KEEP GOING — one class's bad storage must not
                    # abandon the other classes' shutdown drain
                    silo.vector._mark_dirty(cls, keys)
                    first_error = first_error or e
            if n:
                silo.stats.increment("vector.storage.flushed", n)
            if first_error is not None:
                raise first_error
            return n

        async def flusher() -> None:
            while True:
                await asyncio.sleep(flush_period)
                if silo.status in ("Dead", "Stopped"):
                    return  # kill skips lifecycle stops; die with the silo
                try:
                    await flush_all()
                except Exception:  # noqa: BLE001 — keep flushing next period
                    import logging
                    logging.getLogger("orleans.vector").exception(
                        "write-behind flush failed")

        def start() -> None:
            state["task"] = asyncio.get_running_loop().create_task(flusher())

        async def stop() -> None:
            task, state["task"] = state["task"], None
            if task is not None:
                task.cancel()
                # await the cancelled flusher so its BaseException re-mark
                # lands BEFORE the final drain below — otherwise keys a
                # mid-flight flush had already drained would be re-marked
                # after stop's pass and silently never persisted
                await asyncio.gather(task, return_exceptions=True)
            # final write-behind drain: strict — a failure here has no
            # next period to retry, so it must surface out of stop()
            await flush_all(strict=True)

        from ..runtime.silo import ServiceLifecycleStage

        silo.subscribe_lifecycle(
            ServiceLifecycleStage.APPLICATION_SERVICES, start, stop)

    def _install_checkpoints(silo) -> None:
        import asyncio

        from ..runtime.silo import ServiceLifecycleStage
        from ..storage.checkpoint import VectorCheckpointer

        ckpt = VectorCheckpointer(silo.vector, checkpoint_dir,
                                  max_to_keep=checkpoint_keep)
        silo.vector_checkpointer = ckpt
        state = {"task": None, "step": 0, "quit": None}

        async def snapshotter() -> None:
            # cooperative shutdown (never cancelled): orbax managers are
            # not thread-safe, so a write must never overlap the final
            # stop() save — stop sets `quit` and AWAITS this task, which
            # finishes any in-flight write before exiting
            while True:
                try:
                    await asyncio.wait_for(state["quit"].wait(),
                                           timeout=checkpoint_period)
                    return  # graceful stop requested
                except asyncio.TimeoutError:
                    pass
                if silo.status in ("Dead", "Stopped", "ShuttingDown"):
                    return  # killed silos must not overwrite the successor's
                            # checkpoints (kill skips lifecycle stops)
                try:
                    # capture on the loop (donation safety), write in a
                    # thread — a multi-GB table write must not stall
                    # membership probes and gateway traffic
                    state["step"] += 1
                    captured = ckpt.capture()
                    await asyncio.to_thread(ckpt.write, state["step"],
                                            captured)
                    silo.stats.increment("vector.checkpoints")
                except Exception:  # noqa: BLE001 — next period retries
                    import logging
                    logging.getLogger("orleans.vector").exception(
                        "table checkpoint failed")

        def start() -> None:
            state["quit"] = asyncio.Event()
            latest = ckpt.latest_step()
            if latest is not None:
                ckpt.restore(latest)  # whole-silo resume before serving
                state["step"] = latest
            state["task"] = asyncio.get_running_loop().create_task(
                snapshotter())

        async def stop() -> None:
            task, state["task"] = state["task"], None
            if task is not None:
                state["quit"].set()
                await task  # in-flight write completes before the final save
            state["step"] += 1
            ckpt.save(state["step"])  # final snapshot
            ckpt.wait()
            # no ckpt.close(): orbax's manager shutdown tears down an
            # executor shared across managers in this process, breaking a
            # successor silo's checkpointer (restart-in-process is exactly
            # the TestCluster/resume scenario); wait() has already settled
            # all writes

        silo.subscribe_lifecycle(
            ServiceLifecycleStage.APPLICATION_SERVICES, start, stop)

    return builder.configure(install)
